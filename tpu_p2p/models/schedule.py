"""Unified tick-schedule IR + one executor for every pipeline schedule.

Round 14 tentpole (ROADMAP "Unified schedule IR"). The repo grew four
hand-written pipeline executors — the GPipe scan
(:mod:`tpu_p2p.models.pipeline`), plain 1F1B and the interleaved
virtual-stage schedule (:mod:`tpu_p2p.models.pipeline_1f1b` /
:mod:`~.pipeline_interleaved`), and the two flagship executors riding
them — so every schedule improvement multiplied code paths (PR 5's
wave knob had to touch all of them separately). This module factors
the schedule itself out of the executors:

- **The IR.** A :class:`TickProgram` is an ordered list of
  :class:`Tick`\\ s, each ``{compute: (kind, device, chunk,
  microbatch) ops, hops: (payload, edge set)}`` — a pure host-side
  description, no arrays, no jax. Op kinds: ``fwd``, ``bwd`` (the
  fused input+weight backward the legacy executors run),
  ``bwd_input`` (dx only — the pipeline's critical path) and
  ``bwd_weight`` (dW only — bubble filler), the Qi et al. zero-bubble
  split (PAPERS.md, arXiv:2401.10241).
- **Compilers.** :func:`compile_gpipe`, :func:`compile_1f1b`,
  :func:`compile_interleaved` emit the three legacy schedules as IR
  programs (1F1B/interleaved reuse the proven greedy builder in
  ``pipeline_interleaved``, so the tick tables are byte-identical to
  what the legacy executors run); :func:`compile_zb` emits the new
  ZB-H1-style schedule — plain 1F1B with the backward split into
  ``bwd_input`` on the critical path and ``bwd_weight`` ticks filling
  the warmup/drain bubbles, per-stage dW order kept in microbatch
  order so the step stays BITWISE equal to the fused executor (the
  accumulation sequence per stage is unchanged; only *when* each term
  lands moves).
- **One executor.** :func:`make_tick_train_step` runs ANY program:
  forward-only programs execute as a masked ``lax.scan`` whose
  backward comes from autodiff (the GPipe contract); programs with
  backward ticks run the manual per-tick ``jax.vjp`` machinery
  (rematerialized forwards, interval-colored stash — the
  ``pipeline_interleaved`` design, generalized with split-backward
  tables). Every stage hop ships through
  :func:`tpu_p2p.parallel.collectives.chunked_ppermute_compute`, so
  ``pp_overlap="wave"`` and ``transport="pallas_dma"`` are per-tick
  lowering choices of the ONE ship site, not executor rewrites
  (``chunks<=1`` + ``transport="xla"`` is bitwise the legacy one-shot
  ``ppermute``).
- **Analytic accounting.** :func:`bubble_fraction` prices a program's
  idle share under the uniform cost model (``fwd`` = ``bwd_input`` =
  ``bwd_weight`` = 1, fused ``bwd`` = 2 — the standard
  backward-costs-twice-the-forward count), and :func:`price_program`
  prices each tick's hops with the SAME busbw conventions as the
  collective ledger (:func:`tpu_p2p.obs.ledger.wire_bytes`), so a
  schedule's transport bill reads in the obs report's units before a
  single step runs. These are the ``pp_bubble_frac_{1f1b,zb}`` bench
  headlines (docs/schedule_ir.md has the compiler table and the
  ZB-H1 diagram).

Why the zero-bubble split stays bitwise (the contract
tests/test_schedule.py pins): ``jax.vjp`` of the stage block against
only its input (``bwd_input``) and later against only its params
(``bwd_weight``, forward rematerialized from the same stashed
activation and the same stashed incoming gradient) computes exactly
the arithmetic the fused vjp computes, just partitioned; no sum is
reassociated because each stage's dW terms still accumulate in
microbatch order and the loss terms still accumulate at the last
stage's ``bwd_input`` ticks in microbatch order.

**Cost-proportional tick lowering (round 16).** The masked-SPMD
execution above runs EVERY tick's full compute body on EVERY rank
and discards idle work through where-masks — wall clock tracks
``ticks x full-body cost``, so the analytic bubble win never cashed
out as measured step time (bench nulled the pp>1 measured pair with
exactly that reason). :func:`lower` now takes
``tick_lowering="masked"|"switch"`` (one
:data:`tpu_p2p.config.TICK_LOWERINGS` definition): ``"switch"``
compiles the program into per-rank tick timelines — an ``op_code``
table ``[T, devices]`` indexing a compact per-program op table
(``noop`` plus whichever of ``fwd``/``bwd``/``bwd_input``/
``bwd_weight`` the program issues) — and the executors dispatch each
rank's tick body through ONE ``jax.lax.switch`` over that table, so
a rank whose tick is idle pays only the branch select, the stash
bookkeeping, and the collective hop it participates in (hops stay
outside the switch: every rank must join the ``ppermute`` every
tick). The branch bodies are the masked bodies minus the masks —
same primitives, same operands, same accumulation order — so the
two lowerings are BITWISE equal in value on every parity mesh, and
every compiled schedule (zb today, ZB-V/interleaved variants
tomorrow) inherits the cost-proportional wall clock for free
(docs/schedule_ir.md has the dispatch anatomy and when masked still
wins).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpu_p2p.config import TICK_LOWERINGS
from tpu_p2p.obs import ledger as _ledger

Edge = Tuple[int, int]

# Canonical kind order of the compact switch op table: index 0 is
# always "noop"; a program's table then carries, in this order, only
# the kinds it actually issues — so a zb program dispatches over
# (noop, fwd, bwd_input, bwd_weight) and a fused program over
# (noop, fwd, bwd), and lax.switch never traces a branch the program
# cannot take.
_SWITCH_KIND_ORDER = ("fwd", "bwd", "bwd_input", "bwd_weight")

# Analytic op costs in forward-units: the fused backward computes both
# dx and dW against a rematerialized forward (~2x the forward's
# FLOPs). Under the true ZB-H1 split (tpu_p2p/models/zb_split.py) the
# fused backward trace is PARTITIONED, not re-run: ``bwd_input``
# carries the remat + dx chain (~1 forward-unit) and ``bwd_weight``
# replays only the dW GEMM contractions against the stashed boundary —
# roughly one GEMM per layer where the forward pays one GEMM plus the
# activation chain, hence below 1.0. Bubble fractions derived from
# these are schedule properties, not measurements.
OP_COST = {
    "fwd": 1.0,
    "bwd": 2.0,
    "bwd_input": 1.0,
    "bwd_weight": 0.5,
}

OP_KINDS = tuple(OP_COST)


@dataclass(frozen=True)
class TickOp:
    """One compute op: ``device`` runs ``kind`` for local chunk
    ``chunk`` (virtual stage ``device + chunk * devices``) of
    microbatch ``microbatch``."""

    kind: str
    device: int
    chunk: int
    microbatch: int


@dataclass(frozen=True)
class TickHop:
    """One collective hop issued this tick: ``payload`` names what
    rides the wire (``activation`` fwd ships, ``gradient`` bwd
    ships); ``edges`` is the ``ppermute`` edge set."""

    payload: str
    edges: Tuple[Edge, ...]


@dataclass(frozen=True)
class Tick:
    compute: Tuple[TickOp, ...]
    hops: Tuple[TickHop, ...] = ()


@dataclass(frozen=True)
class TickProgram:
    """An ordered tick schedule over ``devices`` pp ranks, each
    holding ``chunks`` local virtual-stage chunks, processing
    ``microbatches`` microbatches."""

    name: str
    devices: int
    chunks: int
    microbatches: int
    ticks: Tuple[Tick, ...]

    @property
    def num_ticks(self) -> int:
        return len(self.ticks)

    @property
    def has_backward(self) -> bool:
        return any(op.kind != "fwd" for t in self.ticks
                   for op in t.compute)

    @property
    def has_split_backward(self) -> bool:
        return any(op.kind in ("bwd_input", "bwd_weight")
                   for t in self.ticks for op in t.compute)


# ------------------------------------------------------------ analysis


def bubble_fraction(program: TickProgram) -> float:
    """Idle share of the program under :data:`OP_COST`: each tick is a
    device-synchronous barrier costing the most expensive op issued in
    it, so ``1 - busy/(devices * span)`` is the fraction of
    device-ticks spent waiting — the pipeline bubble. GPipe's forward
    program yields the classic ``(S-1)/(M+S-1)``; the zero-bubble
    split beats fused 1F1B because ``bwd_weight`` ticks fill
    warmup/drain holes and the gradient wave crosses stages at
    ``bwd_input`` (1 unit) speed instead of fused-``bwd`` (2 unit)
    speed."""
    n = program.devices
    span = 0.0
    busy = [0.0] * n
    for tick in program.ticks:
        span += max((OP_COST[op.kind] for op in tick.compute),
                    default=1.0)
        for op in tick.compute:
            busy[op.device] += OP_COST[op.kind]
    if span <= 0:
        return 0.0
    return 1.0 - sum(busy) / (n * span)


def per_rank_idle(program: TickProgram) -> List[dict]:
    """Per-rank idle accounting under :data:`OP_COST` — the rank-level
    decomposition of :func:`bubble_fraction`: for each device, its
    busy/idle cost split, its own bubble fraction, and its explicit
    ``idle_spans`` — maximal ``[start_tick, end_tick)`` runs of ticks
    where the rank issues no compute op. Under the masked lowering
    those spans are where-masked full bodies (the rank still pays
    them); under the switch lowering they are genuinely idle — which
    is exactly what ``python -m tpu_p2p obs`` renders them to show
    (measured-vs-analytic bubble per rank)."""
    n = program.devices
    tick_cost = [max((OP_COST[op.kind] for op in t.compute),
                     default=1.0) for t in program.ticks]
    span = sum(tick_cost)
    out: List[dict] = []
    for d in range(n):
        busy = 0.0
        spans: List[List[int]] = []
        for t, tick in enumerate(program.ticks):
            ops = [op for op in tick.compute if op.device == d]
            if ops:
                busy += sum(OP_COST[op.kind] for op in ops)
            elif spans and spans[-1][1] == t:
                spans[-1][1] = t + 1
            else:
                spans.append([t, t + 1])
        idle = max(span - busy, 0.0)
        out.append({
            "device": d,
            "busy_cost": busy,
            "idle_cost": idle,
            "bubble_frac": (idle / span) if span > 0 else 0.0,
            "idle_spans": [tuple(s) for s in spans],
        })
    return out


def price_program(program: TickProgram, payload_bytes: int,
                  topology=None) -> dict:
    """Analytic transport bill of one program execution, priced with
    the collective ledger's busbw conventions
    (:func:`tpu_p2p.obs.ledger.wire_bytes` — per directed link for the
    permute family): per-tick rows plus totals, the same units
    ``python -m tpu_p2p obs`` prints for a *measured* run. ``gradient``
    hops carry float32 cotangents; callers pass the per-payload byte
    count they care about (the executors ship one microbatch shard per
    hop). ``per_rank`` prices each rank's idle ticks explicitly
    (:func:`per_rank_idle`) — the bubble decomposed to the device
    whose wall clock it is, which is what the cost-proportional
    switch lowering turns from an accounting fiction into genuinely
    idle time.

    ``topology`` (a :class:`tpu_p2p.topo.model.Topology`, round 19 —
    docs/topology.md) upgrades the bill from uniform busbw units to
    PER-LINK pricing: every hop runs its edges concurrently, so each
    hop's predicted wall time is the payload over its slowest
    effective link (:meth:`~tpu_p2p.topo.model.Topology.ship_time_s`)
    — rows gain ``hop_s`` / ``bottleneck_edge`` /
    ``bottleneck_gbps``, and the totals gain ``hop_s_total`` plus the
    program-wide ``bottleneck_gbps_min``. The analytic bubble/idle
    accounting (and every pre-round-19 key) is unchanged when
    ``topology`` is None — per-link pricing is additive, never a
    rewrite of the uniform units the gate history is denominated in."""
    rows: List[dict] = []
    total_wire = 0
    total_hop_s = 0.0
    min_gbps = None
    for i, tick in enumerate(program.ticks):
        for hop in tick.hops:
            wire = _ledger.wire_bytes("ppermute", program.devices,
                                      payload_bytes)
            row = {
                "tick": i,
                "payload": hop.payload,
                "edges": hop.edges,
                "wire_bytes": wire,
            }
            if topology is not None and hop.edges:
                # REPORTING view (penalty off): the bill predicts
                # what the wire would do, not the avoidance bias the
                # optimizers steer by (Topology.ship_time_s).
                hop_s = topology.ship_time_s(payload_bytes, hop.edges,
                                             effective=False)
                bneck = topology.bottleneck_edge(hop.edges,
                                                 effective=False)
                gbps = topology.link_gbps(*bneck)
                row.update({
                    "hop_s": hop_s,
                    "bottleneck_edge": bneck,
                    "bottleneck_gbps": gbps,
                })
                total_hop_s += hop_s
                min_gbps = gbps if min_gbps is None \
                    else min(min_gbps, gbps)
            rows.append(row)
            total_wire += wire
    bill = {
        "name": program.name,
        "ticks": program.num_ticks,
        "hops": len(rows),
        "wire_bytes_total": total_wire,
        "bubble_frac": bubble_fraction(program),
        "per_rank": per_rank_idle(program),
        "rows": rows,
    }
    if topology is not None:
        bill["hop_s_total"] = total_hop_s
        bill["bottleneck_gbps_min"] = min_gbps
        bill["topology_source"] = topology.source
    return bill


# ----------------------------------------------------------- compilers


def _ring_edges(n: int) -> Tuple[Edge, ...]:
    return tuple((i, (i + 1) % n) for i in range(n))


def _ring_edges_rev(n: int) -> Tuple[Edge, ...]:
    return tuple(((i + 1) % n, i) for i in range(n))


def _chain_edges(n: int) -> Tuple[Edge, ...]:
    return tuple((i, i + 1) for i in range(n - 1))


def compile_gpipe(microbatches: int, devices: int) -> TickProgram:
    """The GPipe forward schedule as an IR program: tick ``t`` runs
    stage ``s``'s forward of microbatch ``t - s`` (bubble ticks
    elsewhere), activations hopping the no-wraparound neighbor edges.
    The backward is autodiff's mirror — the executor differentiates
    through the tick scan, exactly the legacy
    :func:`tpu_p2p.models.pipeline.pipeline_apply_local` contract."""
    m, n = int(microbatches), int(devices)
    if m < 1 or n < 1:
        raise ValueError(f"need microbatches >= 1, devices >= 1; "
                         f"got {m}, {n}")
    hops = (TickHop("activation", _chain_edges(n)),) if n > 1 else ()
    ticks = []
    for t in range(m + n - 1):
        ops = tuple(
            TickOp("fwd", s, 0, t - s)
            for s in range(n) if 0 <= t - s < m
        )
        ticks.append(Tick(compute=ops, hops=hops))
    return TickProgram(name="gpipe", devices=n, chunks=1,
                       microbatches=m, ticks=tuple(ticks))


def compile_interleaved(microbatches: int, devices: int,
                        chunks: int) -> TickProgram:
    """The interleaved (Megatron-style) 1F1B schedule as an IR
    program, emitted from the SAME greedy builder the legacy executor
    runs (:func:`tpu_p2p.models.pipeline_interleaved.
    build_interleaved_schedule`) — so the compiled program's tick
    tables are byte-identical to the legacy schedule and the executed
    step is bitwise the legacy step."""
    from tpu_p2p.models.pipeline_interleaved import (
        build_interleaved_schedule,
    )

    m, n, v = int(microbatches), int(devices), int(chunks)
    sched = build_interleaved_schedule(m, n, v)
    hops: Tuple[TickHop, ...] = ()
    if n > 1:
        hops = (TickHop("activation", _ring_edges(n)),
                TickHop("gradient", _ring_edges_rev(n)))
    ticks = []
    for t in range(sched.num_ticks):
        ops = []
        for d in range(n):
            if sched.f_mb[t, d] >= 0:
                ops.append(TickOp("fwd", d, int(sched.f_cidx[t, d]),
                                  int(sched.f_mb[t, d])))
            if sched.b_mb[t, d] >= 0:
                ops.append(TickOp("bwd", d, int(sched.b_cidx[t, d]),
                                  int(sched.b_mb[t, d])))
        ticks.append(Tick(compute=tuple(ops), hops=hops))
    return TickProgram(name="interleaved" if v > 1 else "1f1b",
                       devices=n, chunks=v, microbatches=m,
                       ticks=tuple(ticks))


def compile_1f1b(microbatches: int, devices: int) -> TickProgram:
    """Plain 1F1B = the ``chunks=1`` degeneration of the interleaved
    schedule — the same identity the legacy executor uses
    (:func:`~tpu_p2p.models.pipeline_1f1b.
    make_pipeline_train_step_1f1b` delegates to the interleaved step
    with ``chunks=1``), so IR-vs-legacy parity is definitional."""
    return compile_interleaved(microbatches, devices, 1)


def compile_zb(microbatches: int, devices: int) -> TickProgram:
    """ZB-H1-style zero-bubble 1F1B: the fused backward splits into
    ``bwd_input`` (dx — the inter-stage critical path) and
    ``bwd_weight`` (dW — no consumer downstream, so it fills bubbles).

    Greedy per-device policy, one op per device per tick like the
    legacy builders: warm up with ``min(M, S - s)`` forwards, then
    cycle F → Bi → W (a ``bwd_weight`` issues right after its
    ``bwd_input`` when nothing on the critical path is ready —
    keeping the activation stash 1F1B-shaped); in the drain, the
    ``bwd_input`` wave crosses one stage per tick (half the fused
    backward's latency) and the opened holes fill with the deferred
    ``bwd_weight`` ticks — which is where the bubble shrinks
    (docs/schedule_ir.md has the diagram).

    Bitwise contract: per stage, ``bwd_weight`` ops issue strictly in
    microbatch order (FIFO over completed ``bwd_input``\\ s), so the
    dW accumulation sequence — and therefore the step — is bitwise
    the fused 1F1B executor's. ``devices == 1`` has no inter-stage
    critical path to shorten (and no bubble to fill), so the compiler
    degrades to the fused schedule — the same size-1 degrade contract
    as every overlap knob.
    """
    m, n = int(microbatches), int(devices)
    if m < 1 or n < 1:
        raise ValueError(f"need microbatches >= 1, devices >= 1; "
                         f"got {m}, {n}")
    if n == 1:
        prog = compile_1f1b(m, 1)
        return TickProgram(name="zb", devices=1, chunks=1,
                           microbatches=m, ticks=prog.ticks)
    s = n
    fwd_tick = np.full((s, m), -1, np.int64)
    bi_tick = np.full((s, m), -1, np.int64)
    next_f = [0] * s
    next_bi = [0] * s
    next_w = [0] * s
    last_kind = [""] * s
    warmup = [min(m, s - st) for st in range(s)]
    ops_at: Dict[int, List[TickOp]] = {}

    t = 0
    guard = 8 * (m + s) + 16
    while any(next_w[st] < m for st in range(s)):
        if t > guard:
            raise RuntimeError(
                f"zb schedule did not converge (M={m}, S={s})"
            )
        for st in range(s):
            def f_ready():
                mb = next_f[st]
                return mb < m and (
                    st == 0 or 0 <= fwd_tick[st - 1, mb] < t
                )

            def b_ready():
                mb = next_bi[st]
                if mb >= m:
                    return False
                if st < s - 1:
                    return 0 <= bi_tick[st + 1, mb] < t
                return 0 <= fwd_tick[st, mb] < t

            def w_avail():
                return next_w[st] < next_bi[st]

            # Preference order: warmup forwards first (the 1F1B fill);
            # after a Bi, its W (memory stays 1F1B-shaped) unless the
            # critical path idles; after a W, feed the pipe (F); after
            # an F, drain (Bi). Unready preferences fall through, and
            # W — always "ready" once its Bi ran — is the filler.
            if next_f[st] < warmup[st]:
                prefs = ("F", "B", "W")
            elif last_kind[st] == "B":
                prefs = ("W", "F", "B")
            elif last_kind[st] == "W":
                prefs = ("F", "B", "W")
            else:
                prefs = ("B", "W", "F")
            for k in prefs:
                if k == "F" and f_ready():
                    mb = next_f[st]
                    fwd_tick[st, mb] = t
                    next_f[st] += 1
                    last_kind[st] = "F"
                    ops_at.setdefault(t, []).append(
                        TickOp("fwd", st, 0, mb))
                    break
                if k == "B" and b_ready():
                    mb = next_bi[st]
                    bi_tick[st, mb] = t
                    next_bi[st] += 1
                    last_kind[st] = "B"
                    ops_at.setdefault(t, []).append(
                        TickOp("bwd_input", st, 0, mb))
                    break
                if k == "W" and w_avail():
                    mb = next_w[st]
                    next_w[st] += 1
                    last_kind[st] = "W"
                    ops_at.setdefault(t, []).append(
                        TickOp("bwd_weight", st, 0, mb))
                    break
        t += 1

    hops = (TickHop("activation", _ring_edges(n)),
            TickHop("gradient", _ring_edges_rev(n)))
    ticks = tuple(
        Tick(compute=tuple(ops_at.get(i, ())), hops=hops)
        for i in range(t)
    )
    return TickProgram(name="zb", devices=n, chunks=1,
                       microbatches=m, ticks=ticks)


# ------------------------------------------------------------ lowering


@dataclass(frozen=True)
class LoweredProgram:
    """Executable form of a :class:`TickProgram`: per-tick int32
    tables ``[T, devices]`` (−1 = no op) plus interval-colored stash
    slot counts — the exact table family the legacy interleaved
    executor runs, extended with ``w_*`` tables for split-backward
    programs. Forward-only programs carry just the feed/record
    tables.

    ``lowering`` names how the executor runs the tables:
    ``"masked"`` = every rank traces every tick body, idle work
    where-masked (the legacy execution); ``"switch"`` = per-rank tick
    timelines — ``tables["op_code"]`` indexes ``op_table`` (a compact
    per-program kind tuple, ``op_table[0] == "noop"`` always) and the
    tick body is ONE ``lax.switch`` over it. Both lowerings execute
    the same ops on the same operands in the same order, so the step
    is bitwise identical; only what idle ranks pay differs."""

    program: TickProgram
    forward_only: bool
    split: bool
    act_slots: int
    grad_slots: int
    fwd_edges: Tuple[Edge, ...]
    bwd_edges: Tuple[Edge, ...]
    tables: Dict[str, np.ndarray]
    lowering: str = "masked"
    op_table: Tuple[str, ...] = ("noop",)
    # Split programs only: slot count of the boundary stash — the
    # phase1→phase2 values (per-layer cotangents + the activations
    # each dW contraction reads; tpu_p2p/models/zb_split.py) parked
    # between a microbatch's bwd_input and bwd_weight ticks,
    # interval-colored like the activation/gradient stashes.
    bnd_slots: int = 0


def _op_ticks(program: TickProgram):
    """→ per-virtual-stage op tick tables ``[s_virt, m]`` (−1 where
    the program never issues the op)."""
    n, v, m = program.devices, program.chunks, program.microbatches
    s_virt = n * v
    fwd = np.full((s_virt, m), -1, np.int64)
    bwd = np.full((s_virt, m), -1, np.int64)   # bwd or bwd_input
    wgt = np.full((s_virt, m), -1, np.int64)   # bwd_weight
    for t, tick in enumerate(program.ticks):
        for op in tick.compute:
            sv = op.device + op.chunk * n
            tbl = {"fwd": fwd, "bwd": bwd, "bwd_input": bwd,
                   "bwd_weight": wgt}[op.kind]
            if tbl[sv, op.microbatch] >= 0:
                raise ValueError(
                    f"{program.name}: duplicate {op.kind} for virtual "
                    f"stage {sv} microbatch {op.microbatch}"
                )
            tbl[sv, op.microbatch] = t
    return fwd, bwd, wgt


def _switch_tables(program: TickProgram):
    """→ ``(op_table, op_code [T, devices])`` for the switch lowering:
    the compact per-program kind tuple (``noop`` first, then the
    kinds the program issues in :data:`_SWITCH_KIND_ORDER`) and the
    per-rank tick timeline indexing it. The one-op-per-device-per-tick
    discipline every compiler keeps is what makes a single branch
    index per (tick, rank) sufficient — a program violating it cannot
    lower to switch and fails loudly here."""
    kinds = {op.kind for t in program.ticks for op in t.compute}
    op_table = ("noop",) + tuple(k for k in _SWITCH_KIND_ORDER
                                 if k in kinds)
    code_of = {k: i for i, k in enumerate(op_table)}
    op_code = np.zeros((program.num_ticks, program.devices), np.int32)
    for t, tick in enumerate(program.ticks):
        for op in tick.compute:
            if op_code[t, op.device] != 0:
                raise ValueError(
                    f"{program.name}: device {op.device} has more "
                    f"than one compute op at tick {t} — the switch "
                    "lowering dispatches one branch per rank per tick"
                )
            op_code[t, op.device] = code_of[op.kind]
    return op_table, op_code


def lower(program: TickProgram,
          tick_lowering: str = "masked") -> LoweredProgram:
    """Lower an IR program to executor tables.

    Stash slots are interval-colored per device with the SAME
    deterministic coloring (and the same interval construction order)
    as the legacy builder
    (:func:`~tpu_p2p.models.pipeline_1f1b._color_intervals`), so a
    program compiled from the legacy schedule lowers to the legacy
    slot assignment exactly — the bitwise IR-vs-executor contract.
    Split programs keep the fused activation/gradient lifetimes (both
    stashes release at the ``bwd_input`` tick — phase1 consumes them
    there); what the deferred ``bwd_weight`` tick reads instead is the
    boundary stash (``b_bnd`` write slot at the Bi tick, ``w_bnd``
    read slot at the W tick), interval-colored over each microbatch's
    Bi→W span and holding exactly the phase1→phase2 values of the
    split backward (tpu_p2p/models/zb_split.py).

    ``tick_lowering="switch"`` additionally emits the per-rank
    ``op_code`` timeline over the program's compact ``op_table`` (see
    :class:`LoweredProgram`); ``"masked"`` keeps the legacy tables
    byte-identical to round 14's."""
    from tpu_p2p.models.pipeline_1f1b import _color_intervals

    if tick_lowering not in TICK_LOWERINGS:
        raise ValueError(
            f"unknown tick_lowering {tick_lowering!r}; expected one "
            f"of {TICK_LOWERINGS}"
        )
    n, v, m = program.devices, program.chunks, program.microbatches
    s_virt = n * v
    T = program.num_ticks
    fwd_edges = next((h.edges for t in program.ticks for h in t.hops
                      if h.payload == "activation"), ())
    bwd_edges = next((h.edges for t in program.ticks for h in t.hops
                      if h.payload == "gradient"), ())
    fwd_tick, bwd_tick, w_tick = _op_ticks(program)

    op_table: Tuple[str, ...] = ("noop",)
    op_code = None
    if tick_lowering == "switch":
        op_table, op_code = _switch_tables(program)

    if not program.has_backward:
        if (fwd_tick < 0).any():
            raise ValueError(f"{program.name}: forward ops missing")
        if tick_lowering == "switch" and v != 1:
            raise ValueError(
                f"{program.name}: the switch lowering of forward-only "
                "programs supports chunks=1 only (no chunked "
                "forward-only compiler exists)"
            )
        feed_mb = np.full((T,), -1, np.int32)
        out_mb = np.full((T,), -1, np.int32)
        for mb in range(m):
            feed_mb[fwd_tick[0, mb]] = mb
            out_mb[fwd_tick[s_virt - 1, mb]] = mb
        tables = {"feed_mb": feed_mb, "out_mb": out_mb}
        if op_code is not None:
            tables["op_code"] = op_code
        return LoweredProgram(
            program=program, forward_only=True, split=False,
            act_slots=0, grad_slots=0,
            fwd_edges=tuple(fwd_edges), bwd_edges=(),
            tables=tables, lowering=tick_lowering, op_table=op_table,
        )

    split = program.has_split_backward
    if (fwd_tick < 0).any() or (bwd_tick < 0).any():
        raise ValueError(f"{program.name}: fwd/bwd ops missing")
    if split and (w_tick < 0).any():
        raise ValueError(f"{program.name}: bwd_weight ops missing")

    # Interval coloring, per device, in the legacy builder's exact
    # construction order (chunk-major then microbatch). Activation and
    # gradient lifetimes are fused-shaped even for split programs —
    # phase1 drains both at the bwd_input tick; only the boundary
    # stash (below) spans Bi→W.
    act_slots, grad_slots, bnd_slots = 0, 1, 0
    act_assign: Dict = {}
    grad_assign: Dict = {}
    bnd_assign: Dict = {}
    for d in range(n):
        act_iv: List[Tuple[int, int, object]] = []
        grad_iv: List[Tuple[int, int, object]] = []
        bnd_iv: List[Tuple[int, int, object]] = []
        for c in range(v):
            sv = d + c * n
            for mb in range(m):
                w = (fwd_tick[sv, mb] if sv == 0
                     else fwd_tick[sv - 1, mb] + 1)
                act_iv.append((int(w), int(bwd_tick[sv, mb]),
                               (sv, mb)))
                if sv < s_virt - 1:
                    grad_iv.append((int(bwd_tick[sv + 1, mb] + 1),
                                    int(bwd_tick[sv, mb]), (sv, mb)))
                if split:
                    bnd_iv.append((int(bwd_tick[sv, mb]),
                                   int(w_tick[sv, mb]), (sv, mb)))
        cnt, assign = _color_intervals(act_iv)
        act_slots = max(act_slots, cnt)
        act_assign.update(assign)
        if grad_iv:
            cnt, assign = _color_intervals(grad_iv)
            grad_slots = max(grad_slots, cnt)
            grad_assign.update(assign)
        if bnd_iv:
            cnt, assign = _color_intervals(bnd_iv)
            bnd_slots = max(bnd_slots, cnt)
            bnd_assign.update(assign)

    tables = {
        k: np.full((T, n), -1, np.int32)
        for k in ("f_mb", "f_cidx", "f_slot", "b_mb", "b_cidx",
                  "b_slot", "recv_slot", "b_gslot", "grecv_slot",
                  "w_mb", "w_cidx", "b_bnd", "w_bnd")
    }
    for sv in range(s_virt):
        d, c = sv % n, sv // n
        for mb in range(m):
            slot = act_assign[(sv, mb)]
            tables["f_mb"][fwd_tick[sv, mb], d] = mb
            tables["f_cidx"][fwd_tick[sv, mb], d] = c
            tables["f_slot"][fwd_tick[sv, mb], d] = slot
            tables["b_mb"][bwd_tick[sv, mb], d] = mb
            tables["b_cidx"][bwd_tick[sv, mb], d] = c
            tables["b_slot"][bwd_tick[sv, mb], d] = slot
            if sv > 0:
                tables["recv_slot"][fwd_tick[sv - 1, mb] + 1, d] = slot
            if sv < s_virt - 1:
                gs = grad_assign[(sv, mb)]
                tables["b_gslot"][bwd_tick[sv, mb], d] = gs
                tables["grecv_slot"][bwd_tick[sv + 1, mb] + 1, d] = gs
            if split:
                bs = bnd_assign[(sv, mb)]
                tables["b_bnd"][bwd_tick[sv, mb], d] = bs
                tables["w_mb"][w_tick[sv, mb], d] = mb
                tables["w_cidx"][w_tick[sv, mb], d] = c
                tables["w_bnd"][w_tick[sv, mb], d] = bs
    # Per-tick hop elision: a tick with no fwd op anywhere has nothing
    # riding the activation hop (every receive-table entry points at a
    # tick FOLLOWING a real op, so an elided hop's payload is never
    # read) — likewise the gradient hop on ticks with no bwd/bwd_input
    # op. Whole-tick properties, identical on every rank, so the
    # executor can skip the collective without a rank-divergent
    # branch. This is where the split schedule stops paying for its
    # longer tick timeline: zb's W-rich drain ticks ship nothing.
    ship_y = np.zeros((T,), np.int32)
    ship_g = np.zeros((T,), np.int32)
    for t, tick_ in enumerate(program.ticks):
        for op in tick_.compute:
            if op.kind == "fwd":
                ship_y[t] = 1
            elif op.kind in ("bwd", "bwd_input"):
                ship_g[t] = 1
    tables["ship_y"] = ship_y
    tables["ship_g"] = ship_g
    if op_code is not None:
        tables["op_code"] = op_code
    return LoweredProgram(
        program=program, forward_only=False, split=split,
        act_slots=act_slots, grad_slots=grad_slots,
        fwd_edges=tuple(fwd_edges), bwd_edges=tuple(bwd_edges),
        tables=tables, lowering=tick_lowering, op_table=op_table,
        bnd_slots=bnd_slots,
    )


# ------------------------------------------------------------ executor


def _ship(y, axis, edges, wave: bool, pp_chunks: int, transport: str,
          label: str):
    """The ONE stage-hop ship site: every hop lowers through
    :func:`collectives.chunked_ppermute_compute`, so the wave schedule
    (``chunks > 1``) and the raw-DMA transport are per-tick lowering
    choices — ``chunks<=1`` + ``"xla"`` is bitwise the one-shot
    instrumented ``ppermute``."""
    from tpu_p2p.parallel import collectives as C

    return C.chunked_ppermute_compute(
        lambda c, _i: c, y, axis, edges, chunk_dim=1,
        chunks=(pp_chunks if wave else 1), transport=transport,
        label=label,
    )


def _tick_stamp(tick_times, my, row, phase, *deps):
    """Emit ONE flight-recorder boundary stamp (obs/tickprof.py).

    ``tick_times is None`` (the default everywhere) compiles to
    NOTHING — no callback, no ``_tick`` column, a bitwise-identical
    traced program. When set, a ``jax.debug.callback`` records
    ``(rank, tick, phase, host perf_counter)``; the ``deps`` values
    are summed into a dead scalar argument purely to sequence the
    stamp after the tick's real work (data dependence is the only
    ordering the runtime honors). ``stop_gradient`` keeps the stamp
    out of autodiff; the step values are untouched either way."""
    if tick_times is None:
        return
    import jax
    import jax.numpy as jnp

    dep = jnp.float32(0)
    for d in deps:
        dep = dep + jax.lax.stop_gradient(
            jnp.asarray(d).reshape(-1)[0].astype(jnp.float32))
    jax.debug.callback(tick_times.record, my, row["_tick"],
                       jnp.int32(phase), dep)


def _tick_seed(tick_times, my, x_mb):
    """The pre-scan seed stamp: tick ``-1``, phase 1 — bounds tick
    0's duration and delimits step rounds in the recorded stream."""
    if tick_times is None:
        return
    import jax
    import jax.numpy as jnp

    jax.debug.callback(
        tick_times.record, my, jnp.int32(-1), jnp.int32(1),
        jax.lax.stop_gradient(
            jnp.asarray(x_mb).reshape(-1)[0].astype(jnp.float32)))


def _tick_rows(lowered: "LoweredProgram", tick_times):
    """The scanned row pytree; carries a ``_tick`` index column ONLY
    when the flight recorder is on (hooks off ⇒ identical rows)."""
    import jax.numpy as jnp

    rows = {k: jnp.asarray(v) for k, v in lowered.tables.items()}
    if tick_times is not None:
        rows["_tick"] = jnp.arange(len(lowered.tables["ship_y"]),
                                   dtype=jnp.int32)
    return rows


def tick_forward_local(block_fn: Callable, params_local, x_mb,
                       lowered: LoweredProgram, axis: str,
                       pp_overlap: str = "none", pp_chunks: int = 1,
                       transport: str = "xla", tick_times=None):
    """Run a forward-only program — call inside ``shard_map``.

    The IR-driven twin of :func:`tpu_p2p.models.pipeline.
    pipeline_apply_local`: identical per-tick arithmetic (feed gate,
    masked block, last-stage record, psum replicate), with the tick's
    feed/record indices read from the lowered tables instead of
    recomputed from the tick counter — so the executed values are
    bitwise the legacy scan's. Differentiable end to end (the GPipe
    backward contract).

    Under the switch lowering each rank dispatches its tick through
    ``lax.switch`` over the (noop, fwd) op table: idle ranks skip the
    block entirely and ship zeros. Recorded outputs only ever read
    active ticks (a schedule property), and idle-tick cotangents are
    exact zeros under the masked lowering, so values AND autodiff
    gradients stay bitwise the masked scan's."""
    import jax
    import jax.numpy as jnp

    from tpu_p2p.parallel import collectives as C

    n = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    m = x_mb.shape[0]
    wave = pp_overlap == "wave" and pp_chunks > 1 and n > 1
    edges = lowered.fwd_edges
    switch = lowered.lowering == "switch"
    zero = jax.lax.pcast(jnp.zeros_like(x_mb[0]), (axis,),
                         to="varying")

    def tick_body(prev_in, outputs, row):
        """One rank's active fwd tick — shared verbatim between the
        masked tick (which always runs it) and the switch fwd branch
        (which runs it only when this rank's op_code says fwd)."""
        feed_t = row["feed_mb"]
        mb_idx = jnp.clip(feed_t, 0, m - 1)
        feed = jnp.where(
            feed_t >= 0,
            jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0,
                                         keepdims=False),
            zero,
        )
        x_in = jnp.where(my == 0, feed, prev_in)
        y = block_fn(params_local, x_in)
        rec_t = row["out_mb"]
        upd = jax.lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(rec_t, 0, m - 1), 0
        )
        outputs = jnp.where((my == n - 1) & (rec_t >= 0), upd, outputs)
        return y, outputs

    def tick(carry, row):
        prev_in, outputs = carry
        if switch:
            code = jax.lax.dynamic_index_in_dim(
                row["op_code"], my, 0, keepdims=False)
            y, outputs = jax.lax.switch(
                code,
                [lambda p, o: (zero, o),        # noop
                 lambda p, o: tick_body(p, o, row)],  # fwd
                prev_in, outputs,
            )
        else:
            y, outputs = tick_body(prev_in, outputs, row)
        _tick_stamp(tick_times, my, row, 0, y)
        if n > 1:
            y_next = _ship(y, axis, edges, wave, pp_chunks, transport,
                           label="pp_stage_ship")
        else:
            y_next = zero
        _tick_stamp(tick_times, my, row, 1, y_next)
        return (y_next, outputs), None

    outputs0 = jax.lax.pcast(jnp.zeros_like(x_mb), (axis,),
                             to="varying")
    rows = _tick_rows(lowered, tick_times)
    _tick_seed(tick_times, my, x_mb)
    (_, outputs), _ = jax.lax.scan(tick, (zero, outputs0), rows)
    return C.psum(outputs, axis, label="pp_output_replicate")


def tick_grads_local(block_fn: Callable, loss_grad_fn: Callable,
                     params_local, x_mb, target_mb,
                     lowered: LoweredProgram, axis: str,
                     chunk_rows: int = 1,
                     vma_axes: Tuple[str, ...] = (),
                     dparam_vma=None,
                     pp_overlap: str = "none", pp_chunks: int = 1,
                     transport: str = "xla", tick_times=None):
    """Run a backward-carrying program — call inside ``shard_map``.

    The generalized :func:`tpu_p2p.models.pipeline_interleaved.
    interleaved_grads_local`: the same rematerialized manual-vjp tick
    body, masked table lookups, and interval-colored stashes, with two
    build-time extensions —

    - fused programs (``bwd`` ticks) trace the legacy body exactly
      (``jax.vjp`` over (params, x) per tick, dchunk accumulated at
      the backward tick) — bitwise the legacy executor;
    - split programs (``bwd_input``/``bwd_weight``) run the TWO
      PHASES of one fused backward trace
      (:func:`tpu_p2p.models.zb_split.split_backward`): phase1 at the
      ``bwd_input`` tick (remat + loss grad + dx — the critical path)
      writes the phase boundary (per-layer cotangents and the
      activations each dW needs) into the interval-colored boundary
      stash; phase2 at the ``bwd_weight`` tick replays only the dW
      GEMM contractions against that stash — no second remat, no
      second vjp chain. The two phases partition the fused equation
      list, and each stage accumulates dW in microbatch order, so the
      step is bitwise the fused executor's (module docstring).

    Under ``lowered.lowering == "switch"`` the tick body dispatches
    through ONE ``lax.switch`` over the program's compact op table
    instead of running every masked body: the branch bodies are the
    masked bodies minus the masks (same primitives, same operands,
    same per-stage accumulation order — bitwise the masked lowering),
    stash receives and the two collective hops stay outside the
    switch (every rank joins every tick's ``ppermute``), and an idle
    rank's tick costs the branch select plus the hop — the
    cost-proportional execution the analytic bubble model assumes
    (module docstring, docs/schedule_ir.md).

    Returns ``(loss_sum replicated over axis, dparams_local)`` — the
    legacy executor's exact contract (same ``vma_axes`` /
    ``dparam_vma`` semantics; see its docstring)."""
    import jax
    import jax.numpy as jnp

    from tpu_p2p.parallel import collectives as C

    prog = lowered.program
    n = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    v = prog.chunks
    m = prog.microbatches
    wave = pp_overlap == "wave" and pp_chunks > 1 and n > 1
    split = lowered.split

    mb_shape = x_mb.shape[1:]
    all_axes = (axis,) + tuple(a for a in vma_axes if a != axis)
    varying = lambda z: jax.lax.pcast(z, all_axes, to="varying")  # noqa: E731
    zero_mb = varying(jnp.zeros(mb_shape, x_mb.dtype))
    x_stash0 = varying(jnp.zeros((lowered.act_slots,) + mb_shape,
                                 x_mb.dtype))
    g_stash0 = varying(jnp.zeros((lowered.grad_slots,) + mb_shape,
                                 jnp.float32))
    if dparam_vma is None:
        dparams0 = jax.tree.map(
            lambda p: varying(jnp.zeros(p.shape, jnp.float32)),
            params_local,
        )
    else:
        dparams0 = jax.tree.map(
            lambda p, ax: jax.lax.pcast(
                jnp.zeros(p.shape, jnp.float32), tuple(ax),
                to="varying"
            ),
            params_local, dparam_vma,
        )

    def pick(table):
        return jax.lax.dynamic_index_in_dim(table, my, 0,
                                            keepdims=False)

    def chunk_of(params, cidx):
        start = jnp.clip(cidx, 0, v - 1) * chunk_rows
        return jax.tree.map(
            lambda p: jax.lax.dynamic_slice_in_dim(p, start,
                                                   chunk_rows, 0),
            params,
        )

    def to_all_vma(z):
        """pcast ``z`` varying over whichever of ``all_axes`` it is
        not already varying on — boundary values mix param-derived
        (pp-varying) and data-derived (fully varying) leaves, and the
        stash they land in is typed over all axes."""
        have = getattr(getattr(z, "aval", None), "vma", frozenset())
        need = tuple(a for a in all_axes if a not in have)
        return jax.lax.pcast(z, need, to="varying") if need else z

    # True ZB-H1 split: trace the fused backward ONCE on this trace's
    # example operands and partition it into the bwd_input phase
    # (remat + dx) and the bwd_weight phase (dW GEMMs only) — see
    # tpu_p2p/models/zb_split.py. Built at trace time, outside the
    # scan, so the scan body only replays the partitioned equations.
    sb = None
    bnd_stash0 = ()
    if split:
        from tpu_p2p.models.zb_split import split_backward

        sb = split_backward(
            block_fn, loss_grad_fn,
            chunk_of(params_local, jnp.int32(0)), zero_mb,
            jax.lax.dynamic_index_in_dim(target_mb, 0, 0,
                                         keepdims=False),
            varying(jnp.zeros(mb_shape, jnp.float32)),
            my == n - 1,
        )
        bnd_stash0 = tuple(
            varying(jnp.zeros((lowered.bnd_slots,) + a.shape,
                              a.dtype))
            for a in sb.boundary_avals
        )

    def stash_recv(x_stash, g_stash, y_recv, g_recv, row):
        """Write the tick's arrivals into their stash slots — shared
        verbatim by BOTH lowerings (receives are mask-gated in each:
        whether a rank receives is a schedule property, not an op)."""
        rs = pick(row["recv_slot"])
        x_stash = jnp.where(
            rs >= 0,
            jax.lax.dynamic_update_index_in_dim(
                x_stash, y_recv, jnp.clip(rs, 0, lowered.act_slots - 1),
                0,
            ),
            x_stash,
        )
        gs_in = pick(row["grecv_slot"])
        g_stash = jnp.where(
            gs_in >= 0,
            jax.lax.dynamic_update_index_in_dim(
                g_stash, g_recv,
                jnp.clip(gs_in, 0, lowered.grad_slots - 1), 0,
            ),
            g_stash,
        )
        return x_stash, g_stash

    def accum_slice(acc, dc, start):
        """Accumulate one param-chunk cotangent into its rows —
        the ONE gradient-accumulate both lowerings run (masked gates
        it with a where; a switch branch runs it only when on)."""
        cur = jax.lax.dynamic_slice_in_dim(acc, start, chunk_rows, 0)
        return jax.lax.dynamic_update_slice_in_dim(
            acc, cur + dc.astype(jnp.float32), start, 0
        )

    def tick(carry, row):
        (x_stash, g_stash, bnd_stash, y_recv, g_recv, dparams,
         loss_acc) = carry
        x_stash, g_stash = stash_recv(x_stash, g_stash, y_recv,
                                      g_recv, row)

        # Backward (fused) / backward-input (split): remat the chunk's
        # forward under vjp. The split runs phase1 of the partitioned
        # fused trace — the same remat + loss-grad + dx equations —
        # and parks the phase boundary for the deferred dW tick.
        b_mb = pick(row["b_mb"])
        b_on = b_mb >= 0
        b_cidx = pick(row["b_cidx"])
        x_saved = jax.lax.dynamic_index_in_dim(
            x_stash,
            jnp.clip(pick(row["b_slot"]), 0, lowered.act_slots - 1),
            0, keepdims=False,
        )
        chunk_b = chunk_of(params_local, b_cidx)
        tgt = jax.lax.dynamic_index_in_dim(
            target_mb, jnp.clip(b_mb, 0, m - 1), 0, keepdims=False,
        )
        b_gslot = jnp.clip(pick(row["b_gslot"]), 0,
                           lowered.grad_slots - 1)
        g_mid = jax.lax.dynamic_index_in_dim(g_stash, b_gslot, 0,
                                             keepdims=False)
        is_last = (my == n - 1) & (b_cidx == v - 1)
        b_start = jnp.clip(b_cidx, 0, v - 1) * chunk_rows

        def accum_at(acc, dc, start, on):
            return jnp.where(on, accum_slice(acc, dc, start), acc)

        if split:
            loss_mb, dx, bnd_vals = sb.phase1(chunk_b, x_saved, tgt,
                                              g_mid, is_last)
            b_bnd = jnp.clip(pick(row["b_bnd"]), 0,
                             lowered.bnd_slots - 1)
            bnd_stash = tuple(
                jnp.where(
                    b_on,
                    jax.lax.dynamic_update_index_in_dim(
                        st, to_all_vma(val), b_bnd, 0),
                    st,
                )
                for st, val in zip(bnd_stash, bnd_vals)
            )
        else:
            y_re, vjp = jax.vjp(block_fn, chunk_b, x_saved)
            loss_mb, g_loss = loss_grad_fn(y_re, tgt)
            g_in = jnp.where(is_last, g_loss, g_mid)
            dchunk, dx = vjp(g_in.astype(y_re.dtype))
            dparams = jax.tree.map(
                lambda acc, dc: accum_at(acc, dc, b_start, b_on),
                dparams, dchunk,
            )
        loss_acc = loss_acc + jnp.where(
            b_on & is_last, loss_mb.astype(jnp.float32), 0.0
        )
        dx = jnp.where(b_on, dx.astype(jnp.float32), 0.0)

        if split:
            # Backward-weight: phase2 — the dW GEMM contractions
            # alone, replayed against the boundary stashed at this
            # microbatch's bwd_input tick. No remat, no vjp chain.
            w_mb = pick(row["w_mb"])
            w_on = w_mb >= 0
            w_cidx = pick(row["w_cidx"])
            w_bnd = jnp.clip(pick(row["w_bnd"]), 0,
                             lowered.bnd_slots - 1)
            bnd_read = tuple(
                jax.lax.dynamic_index_in_dim(st, w_bnd, 0,
                                             keepdims=False)
                for st in bnd_stash
            )
            chunk_w = chunk_of(params_local, w_cidx)
            dchunk_w = sb.phase2(chunk_w, bnd_read)
            w_start = jnp.clip(w_cidx, 0, v - 1) * chunk_rows
            dparams = jax.tree.map(
                lambda acc, dc: accum_at(acc, dc, w_start, w_on),
                dparams, dchunk_w,
            )

        # Forward.
        f_mb = pick(row["f_mb"])
        f_on = f_mb >= 0
        f_cidx = pick(row["f_cidx"])
        f_slot = jnp.clip(pick(row["f_slot"]), 0,
                          lowered.act_slots - 1)
        feed = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(f_mb, 0, m - 1), 0, keepdims=False,
        )
        x_in = jnp.where((my == 0) & (f_cidx == 0), feed,
                         jax.lax.dynamic_index_in_dim(
                             x_stash, f_slot, 0, keepdims=False))
        x_stash = jnp.where(
            f_on,
            jax.lax.dynamic_update_index_in_dim(x_stash, x_in, f_slot,
                                                0),
            x_stash,
        )
        y_f = block_fn(chunk_of(params_local, f_cidx), x_in)
        y_f = jnp.where(f_on, y_f, zero_mb)
        _tick_stamp(tick_times, my, row, 0, y_f, dx,
                    jax.tree.leaves(dparams)[0], loss_acc)

        if n > 1:
            # Hop elision (see lower()): the whole mesh agrees on the
            # per-tick ship flags, so the skipped collective is a
            # mesh-uniform branch — never a rank-divergent one — and
            # an elided hop's payload is read by no receive table.
            y_next = jax.lax.cond(
                row["ship_y"] > 0,
                lambda: _ship(y_f, axis, lowered.fwd_edges, wave,
                              pp_chunks, transport,
                              label="pp_fwd_ship"),
                lambda: y_f,
            )
            g_next = jax.lax.cond(
                row["ship_g"] > 0,
                lambda: _ship(dx, axis, lowered.bwd_edges, wave,
                              pp_chunks, transport,
                              label="pp_bwd_ship"),
                lambda: dx,
            )
        else:
            y_next, g_next = y_f, dx
        _tick_stamp(tick_times, my, row, 1, y_next, g_next)
        return (x_stash, g_stash, bnd_stash, y_next, g_next, dparams,
                loss_acc), None

    # Cost-proportional tick: ONE lax.switch over the program's
    # compact op table. Every branch body below is its masked twin
    # above minus the where-masks — a branch only ever runs when its
    # mask would have been True, so values (and therefore the step)
    # are bitwise the masked lowering's. Stash receives stay before
    # the switch and the hops after it: collectives cannot live
    # inside a rank-divergent branch.
    zero_g = varying(jnp.zeros(mb_shape, jnp.float32))

    def tick_switch(carry, row):
        (x_stash, g_stash, bnd_stash, y_recv, g_recv, dparams,
         loss_acc) = carry
        x_stash, g_stash = stash_recv(x_stash, g_stash, y_recv,
                                      g_recv, row)

        def bwd_front(x_s, g_s):
            """The shared head of both backward kinds: stash read,
            remat operands, target, incoming cotangent — verbatim the
            masked body's lines."""
            b_mb = pick(row["b_mb"])
            b_cidx = pick(row["b_cidx"])
            x_saved = jax.lax.dynamic_index_in_dim(
                x_s,
                jnp.clip(pick(row["b_slot"]), 0,
                         lowered.act_slots - 1),
                0, keepdims=False,
            )
            chunk_b = chunk_of(params_local, b_cidx)
            tgt = jax.lax.dynamic_index_in_dim(
                target_mb, jnp.clip(b_mb, 0, m - 1), 0,
                keepdims=False,
            )
            b_gslot = jnp.clip(pick(row["b_gslot"]), 0,
                               lowered.grad_slots - 1)
            g_mid = jax.lax.dynamic_index_in_dim(g_s, b_gslot, 0,
                                                 keepdims=False)
            is_last = (my == n - 1) & (b_cidx == v - 1)
            return (b_cidx, x_saved, chunk_b, tgt, b_gslot, g_mid,
                    is_last)

        def br_noop(x_s, g_s, bnd_s, dp, la):
            return x_s, g_s, bnd_s, dp, la, zero_mb, zero_g

        def br_fwd(x_s, g_s, bnd_s, dp, la):
            f_mb = pick(row["f_mb"])
            f_cidx = pick(row["f_cidx"])
            f_slot = jnp.clip(pick(row["f_slot"]), 0,
                              lowered.act_slots - 1)
            feed = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(f_mb, 0, m - 1), 0, keepdims=False,
            )
            x_in = jnp.where((my == 0) & (f_cidx == 0), feed,
                             jax.lax.dynamic_index_in_dim(
                                 x_s, f_slot, 0, keepdims=False))
            x_s = jax.lax.dynamic_update_index_in_dim(x_s, x_in,
                                                      f_slot, 0)
            y_f = block_fn(chunk_of(params_local, f_cidx), x_in)
            return x_s, g_s, bnd_s, dp, la, y_f, zero_g

        def br_bwd(x_s, g_s, bnd_s, dp, la):
            (b_cidx, x_saved, chunk_b, tgt, _b_gslot, g_mid,
             is_last) = bwd_front(x_s, g_s)
            y_re, vjp = jax.vjp(block_fn, chunk_b, x_saved)
            loss_mb, g_loss = loss_grad_fn(y_re, tgt)
            g_in = jnp.where(is_last, g_loss, g_mid)
            dchunk, dx = vjp(g_in.astype(y_re.dtype))
            b_start = jnp.clip(b_cidx, 0, v - 1) * chunk_rows
            dp = jax.tree.map(
                lambda acc, dc: accum_slice(acc, dc, b_start),
                dp, dchunk,
            )
            la = la + jnp.where(is_last, loss_mb.astype(jnp.float32),
                                0.0)
            return (x_s, g_s, bnd_s, dp, la, zero_mb,
                    dx.astype(jnp.float32))

        def br_bwd_input(x_s, g_s, bnd_s, dp, la):
            (_b_cidx, x_saved, chunk_b, tgt, _b_gslot, g_mid,
             is_last) = bwd_front(x_s, g_s)
            # Phase1 of the partitioned fused backward (masked twin:
            # the b_on'd phase1 + boundary-stash write).
            loss_mb, dx, bnd_vals = sb.phase1(chunk_b, x_saved, tgt,
                                              g_mid, is_last)
            b_bnd = jnp.clip(pick(row["b_bnd"]), 0,
                             lowered.bnd_slots - 1)
            bnd_s = tuple(
                jax.lax.dynamic_update_index_in_dim(
                    st, to_all_vma(val), b_bnd, 0)
                for st, val in zip(bnd_s, bnd_vals)
            )
            la = la + jnp.where(is_last, loss_mb.astype(jnp.float32),
                                0.0)
            return (x_s, g_s, bnd_s, dp, la, zero_mb,
                    dx.astype(jnp.float32))

        def br_bwd_weight(x_s, g_s, bnd_s, dp, la):
            w_cidx = pick(row["w_cidx"])
            w_bnd = jnp.clip(pick(row["w_bnd"]), 0,
                             lowered.bnd_slots - 1)
            bnd_read = tuple(
                jax.lax.dynamic_index_in_dim(st, w_bnd, 0,
                                             keepdims=False)
                for st in bnd_s
            )
            chunk_w = chunk_of(params_local, w_cidx)
            dchunk_w = sb.phase2(chunk_w, bnd_read)
            w_start = jnp.clip(w_cidx, 0, v - 1) * chunk_rows
            dp = jax.tree.map(
                lambda acc, dc: accum_slice(acc, dc, w_start),
                dp, dchunk_w,
            )
            return x_s, g_s, bnd_s, dp, la, zero_mb, zero_g

        branch_of = {"noop": br_noop, "fwd": br_fwd, "bwd": br_bwd,
                     "bwd_input": br_bwd_input,
                     "bwd_weight": br_bwd_weight}
        code = pick(row["op_code"])
        (x_stash, g_stash, bnd_stash, dparams, loss_acc, y_f, dx) = \
            jax.lax.switch(
                code, [branch_of[k] for k in lowered.op_table],
                x_stash, g_stash, bnd_stash, dparams, loss_acc,
            )
        _tick_stamp(tick_times, my, row, 0, y_f, dx,
                    jax.tree.leaves(dparams)[0], loss_acc)

        if n > 1:
            # Hop elision (see lower()): the whole mesh agrees on the
            # per-tick ship flags, so the skipped collective is a
            # mesh-uniform branch — never a rank-divergent one — and
            # an elided hop's payload is read by no receive table.
            y_next = jax.lax.cond(
                row["ship_y"] > 0,
                lambda: _ship(y_f, axis, lowered.fwd_edges, wave,
                              pp_chunks, transport,
                              label="pp_fwd_ship"),
                lambda: y_f,
            )
            g_next = jax.lax.cond(
                row["ship_g"] > 0,
                lambda: _ship(dx, axis, lowered.bwd_edges, wave,
                              pp_chunks, transport,
                              label="pp_bwd_ship"),
                lambda: dx,
            )
        else:
            y_next, g_next = y_f, dx
        _tick_stamp(tick_times, my, row, 1, y_next, g_next)
        return (x_stash, g_stash, bnd_stash, y_next, g_next, dparams,
                loss_acc), None

    carry0 = (x_stash0, g_stash0, bnd_stash0, zero_mb,
              varying(jnp.zeros(mb_shape, jnp.float32)), dparams0,
              varying(jnp.zeros((), jnp.float32)))
    rows = _tick_rows(lowered, tick_times)
    _tick_seed(tick_times, my, x_mb)
    (_, _, _, _, _, dparams, loss_acc), _ = jax.lax.scan(
        tick_switch if lowered.lowering == "switch" else tick,
        carry0, rows,
    )
    return C.psum(loss_acc, axis, label="pp_loss_replicate"), dparams


def make_tick_train_step(mesh, cfg, program: TickProgram,
                         block_fn: Optional[Callable] = None,
                         lr: float = 1e-2,
                         loss_grad_fn: Optional[Callable] = None,
                         pp_overlap: str = "none", pp_chunks: int = 1,
                         transport: str = "xla",
                         tick_lowering: str = "masked",
                         tick_times=None):
    """ONE jitted SGD step for ANY tick program — the executor every
    schedule compiles to.

    ``cfg`` is a :class:`tpu_p2p.models.pipeline.PipelineConfig`;
    ``cfg.stages`` must equal ``program.devices * program.chunks`` and
    the mesh's ``pp`` axis must match ``program.devices``. Forward-only
    programs (GPipe) differentiate through the tick scan (autodiff
    owns the backward — matching
    :func:`~tpu_p2p.models.pipeline.make_pipeline_train_step`'s loss
    normalization and update bitwise); backward-carrying programs run
    the manual-vjp tick machinery (matching
    :func:`~tpu_p2p.models.pipeline_interleaved.
    make_interleaved_train_step`; params for ``chunks > 1`` programs
    use the device-major layout —
    :func:`~tpu_p2p.models.pipeline_interleaved.
    place_interleaved_params`). ``pp_overlap``/``pp_chunks``/
    ``transport`` lower every stage hop per tick through
    ``chunked_ppermute_compute`` — the one ship site;
    ``tick_lowering="switch"`` runs the cost-proportional per-rank
    dispatch (bitwise the default masked execution, idle ranks
    genuinely idle — module docstring)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_p2p.models.pipeline import (
        _to_microbatches,
        mlp_block,
        pp_param_specs,
    )
    from tpu_p2p.models.pipeline_1f1b import _mse_loss_grad

    block_fn = block_fn or mlp_block
    loss_grad_fn = loss_grad_fn or _mse_loss_grad
    pp = "pp" if "pp" in mesh.axis_names else None
    if pp is None:
        raise ValueError("mesh needs a 'pp' axis for pipeline "
                         "parallelism")
    if mesh.shape[pp] != program.devices:
        raise ValueError(
            f"program compiled for {program.devices} devices; pp axis "
            f"has {mesh.shape[pp]}"
        )
    if cfg.stages != program.devices * program.chunks:
        raise ValueError(
            f"cfg.stages ({cfg.stages}) != program devices x chunks "
            f"({program.devices} x {program.chunks})"
        )
    if cfg.microbatches != program.microbatches:
        raise ValueError(
            f"cfg.microbatches ({cfg.microbatches}) != program "
            f"microbatches ({program.microbatches})"
        )
    lowered = lower(program, tick_lowering=tick_lowering)

    if lowered.forward_only:
        def step(params, x, target):
            def local_loss(p):
                x_mb = _to_microbatches(x, cfg.microbatches)
                y = tick_forward_local(
                    block_fn, p, x_mb, lowered, pp,
                    pp_overlap=pp_overlap, pp_chunks=pp_chunks,
                    transport=transport, tick_times=tick_times,
                )
                return jnp.sum(
                    (y.astype(jnp.float32)
                     - _to_microbatches(target, cfg.microbatches)
                     .astype(jnp.float32)) ** 2
                )

            loss, grads = jax.value_and_grad(local_loss)(params)
            denom = float(np.prod(x.shape))
            new_params = jax.tree.map(
                lambda p, g: (p.astype(jnp.float32)
                              - lr * g / denom).astype(p.dtype),
                params, grads,
            )
            return new_params, loss / denom
    else:
        def step(params, x, target):
            x_mb = _to_microbatches(x, cfg.microbatches)
            t_mb = _to_microbatches(target, cfg.microbatches)
            loss_sum, grads = tick_grads_local(
                block_fn, loss_grad_fn, params, x_mb, t_mb, lowered,
                pp, pp_overlap=pp_overlap, pp_chunks=pp_chunks,
                transport=transport, tick_times=tick_times,
            )
            denom = float(np.prod(x.shape))
            new_params = jax.tree.map(
                lambda p, g: (p.astype(jnp.float32)
                              - lr * g / denom).astype(p.dtype),
                params, grads,
            )
            return new_params, loss_sum / denom

    sm = jax.shard_map(
        step, mesh=mesh,
        in_specs=(pp_param_specs(mesh), P(), P()),
        out_specs=(pp_param_specs(mesh), P()),
    )
    return jax.jit(sm)
