"""Pipeline parallelism — GPipe microbatching over ``ppermute``.

SURVEY.md §2.3's pipeline-parallelism row: the reference has no model
code, but "the communication pattern underlying PP (neighbor
send/recv) is the benchmark's core" — the shift-by-1 ``ppermute`` edge
set of the ``ring`` workload, minus the wraparound. This module
supplies the compute side: a GPipe-style schedule where each device
owns one pipeline stage and activations flow stage→stage+1 through
``ppermute``, so the framework demonstrates PP's real
transfer-compute interleaving, not just the bare hop.

TPU-first design:

- **One jitted program, no data-dependent control flow.** The whole
  ``M + S - 1``-tick schedule (``M`` microbatches, ``S`` stages) is a
  single ``lax.scan``; bubble ticks run the same compute on zero
  inputs and their results are masked out — static shapes, branchless,
  exactly what XLA wants.
- **Stage-major params.** Every stage's weights form one array with a
  leading stage dim sharded over ``pp``
  (``P('pp', ...)``), so each device holds its own stage's slice and
  the block function is identical SPMD code on every stage.
- **Differentiable end-to-end.** ``ppermute`` has a well-defined
  transpose (the reversed edge set), so ``jax.grad`` through the scan
  yields exact pipeline-parallel backprop — verified against a
  single-device oracle in tests/test_pipeline.py.
- Outputs materialize on the last stage (others contribute zeros) and
  are ``psum``-replicated across ``pp`` so the caller sees the full
  ``[B, ...]`` batch everywhere — the loss is then typed replicated
  over ``pp`` and counts once in autodiff, same accounting as the tp
  ``psum`` in :mod:`tpu_p2p.models.ring_transformer`.

Round 14: the schedule also compiles to the unified tick IR
(:func:`tpu_p2p.models.schedule.compile_gpipe`), whose executor runs
it bitwise-equal to this module's scan (docs/schedule_ir.md).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_p2p.parallel import collectives as C

Params = Dict[str, jax.Array]


@dataclass(frozen=True)
class PipelineConfig:
    """A stack of ``stages`` identical residual-MLP blocks."""

    d_model: int = 32
    d_ff: int = 64
    stages: int = 4
    microbatches: int = 4


def init_pipeline_params(cfg: PipelineConfig, seed: int = 0,
                         dtype=jnp.float32) -> Params:
    rng = np.random.default_rng(seed)
    s, d, f = cfg.stages, cfg.d_model, cfg.d_ff

    def w(*shape, fan_in):
        return jnp.asarray(rng.standard_normal(shape) / math.sqrt(fan_in),
                           dtype=dtype)

    return {"w1": w(s, d, f, fan_in=d), "w2": w(s, f, d, fan_in=f)}


def pp_param_specs(mesh: Mesh) -> Dict[str, P]:
    pp = "pp" if "pp" in mesh.axis_names else None
    return {"w1": P(pp, None, None), "w2": P(pp, None, None)}


def mlp_block(stage_params: Params, x):
    """The per-stage compute: one residual MLP block.

    ``stage_params`` leaves carry the local stage slice ``[1, ...]``
    (squeezed here). Zero input → zero output, which is what makes the
    masked bubble ticks harmless.
    """
    w1, w2 = stage_params["w1"][0], stage_params["w2"][0]
    h = jax.nn.gelu(jnp.einsum("btd,df->btf", x,
                               w1, preferred_element_type=jnp.float32))
    return x + jnp.einsum("btf,fd->btd", h.astype(x.dtype), w2,
                          preferred_element_type=jnp.float32).astype(x.dtype)


def pipeline_apply_local(block_fn: Callable, params_local: Params, x_mb,
                         axis: str, pp_overlap: str = "none",
                         pp_chunks: int = 1):
    """GPipe schedule body — call inside ``shard_map`` over ``axis``.

    ``x_mb``: microbatched input ``[M, mb, T, D]``, replicated over the
    ``pp`` axis. Returns the full output ``[M, mb, T, D]``, replicated
    (see module docstring for the psum accounting).

    Tick ``t``: stage ``s`` processes microbatch ``t - s`` (zeros
    during fill/drain bubbles); activations hop ``s → s+1`` on the
    no-wraparound neighbor edge set — the PP transport SURVEY.md §2.3
    maps onto this framework's ``ring`` workload.

    ``pp_overlap="wave"`` (with ``pp_chunks > 1``) double-buffers the
    stage hop: the tick's activation ship splits into ``pp_chunks``
    token chunks through :func:`collectives.chunked_ppermute_compute`,
    chunk ``c``'s ``ppermute`` in flight while chunk ``c+1`` (and the
    tick's trailing output-record ops) are still computing — same
    bytes, no extra hops, values elementwise identical to the one-shot
    ship (docs/pp_overlap.md). ``"none"``, ``pp_chunks=1``, or a
    1-sized axis keep the byte-identical monolithic hop.
    """
    s_count = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    m = x_mb.shape[0]
    edges = [(i, i + 1) for i in range(s_count - 1)]
    wave = pp_overlap == "wave" and pp_chunks > 1 and s_count > 1
    # pcast-to-varying: the scan carry is device-varying over pp (axis_index is in
    # the tick), so its initial value must be typed varying too.
    zero = jax.lax.pcast(jnp.zeros_like(x_mb[0]), (axis,), to='varying')

    def tick(carry, t):
        prev_in, outputs = carry
        # Stage 0 consumes microbatch t (zeros outside [0, M)).
        mb_idx = jnp.clip(t, 0, m - 1)
        feed = jnp.where((t >= 0) & (t < m),
                         jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0,
                                                      keepdims=False),
                         zero)
        x_in = jnp.where(my == 0, feed, prev_in)
        y = block_fn(params_local, x_in)
        # Ship to the next stage (last stage's send has no edge). The
        # wave splits the hop into token-chunk waves (identity chunk
        # compute: the block output already exists for the out_t
        # recording below, so only the ship is chunked).
        if wave:
            y_next = C.chunked_ppermute_compute(
                lambda c, _i: c, y, axis, edges, chunk_dim=1,
                chunks=pp_chunks, label="pp_stage_ship")
        else:
            y_next = (C.ppermute(y, axis, edges, label="pp_stage_ship")
                      if s_count > 1 else zero)
        # Last stage: record microbatch t - (S-1) once it's real.
        out_t = t - (s_count - 1)
        upd = jax.lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(out_t, 0, m - 1), 0
        )
        outputs = jnp.where((my == s_count - 1) & (out_t >= 0), upd, outputs)
        return (y_next, outputs), None

    outputs0 = jax.lax.pcast(jnp.zeros_like(x_mb), (axis,), to='varying')
    (_, outputs), _ = jax.lax.scan(
        tick, (zero, outputs0), jnp.arange(m + s_count - 1)
    )
    # Replicate the last stage's outputs to every pp rank.
    return C.psum(outputs, axis, label="pp_output_replicate")


def _to_microbatches(x, m: int):
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    return x.reshape((m, b // m) + x.shape[1:])


def make_pipeline_forward(mesh: Mesh, cfg: PipelineConfig,
                          block_fn: Callable = mlp_block,
                          pp_overlap: str = "none", pp_chunks: int = 1):
    """Jitted pipeline forward: global ``[B, T, D]`` in and out.

    Runs the GPipe program through the tick-schedule IR
    (``compile_gpipe -> lower() -> tick_forward_local``) — bitwise the
    legacy hand-rolled scan (:func:`pipeline_apply_local`, kept as a
    parity fixture; tests/test_schedule.py pins the equivalence).
    """
    from tpu_p2p.models.schedule import (
        compile_gpipe,
        lower,
        tick_forward_local,
    )

    pp = _check_pp_mesh(mesh, cfg)
    lowered = lower(compile_gpipe(cfg.microbatches, cfg.stages))

    def f(params, x):
        x_mb = _to_microbatches(x, cfg.microbatches)
        y_mb = tick_forward_local(block_fn, params, x_mb, lowered, pp,
                                  pp_overlap=pp_overlap,
                                  pp_chunks=pp_chunks)
        return y_mb.reshape(x.shape)

    sm = jax.shard_map(
        f, mesh=mesh,
        in_specs=(pp_param_specs(mesh), P()),
        out_specs=P(),
    )
    return jax.jit(sm)


def _check_pp_mesh(mesh: Mesh, cfg: PipelineConfig) -> str:
    pp = "pp" if "pp" in mesh.axis_names else None
    if pp is None:
        raise ValueError("mesh needs a 'pp' axis for pipeline parallelism")
    if mesh.shape[pp] != cfg.stages:
        raise ValueError(
            f"cfg.stages ({cfg.stages}) != pp axis size ({mesh.shape[pp]})"
        )
    return pp


def make_pipeline_train_step(mesh: Mesh, cfg: PipelineConfig,
                             block_fn: Callable = mlp_block, lr: float = 1e-2,
                             pp_overlap: str = "none", pp_chunks: int = 1):
    """One jitted SGD step through the pipeline schedule.

    Routed through the tick-schedule IR (``compile_gpipe -> lower()``;
    autodiff owns the backward through the tick scan) — bitwise the
    legacy executor, which survives as the
    :func:`make_pipeline_train_step_reference` parity fixture.
    """
    from tpu_p2p.models.schedule import compile_gpipe, make_tick_train_step

    _check_pp_mesh(mesh, cfg)
    return make_tick_train_step(
        mesh, cfg, compile_gpipe(cfg.microbatches, cfg.stages),
        block_fn=block_fn, lr=lr, pp_overlap=pp_overlap,
        pp_chunks=pp_chunks)


def make_pipeline_train_step_reference(mesh: Mesh, cfg: PipelineConfig,
                                       block_fn: Callable = mlp_block,
                                       lr: float = 1e-2,
                                       pp_overlap: str = "none",
                                       pp_chunks: int = 1):
    """Parity fixture: the legacy hand-rolled GPipe step (autodiff over
    :func:`pipeline_apply_local`'s tick-counter scan). Production code
    goes through :func:`make_pipeline_train_step`; tests pin this
    fixture bitwise against the IR path."""
    pp = _check_pp_mesh(mesh, cfg)

    def step(params, x, target):
        def local_loss(p):
            x_mb = _to_microbatches(x, cfg.microbatches)
            y = pipeline_apply_local(block_fn, p, x_mb, pp,
                                     pp_overlap=pp_overlap,
                                     pp_chunks=pp_chunks)
            return jnp.sum(
                (y.astype(jnp.float32)
                 - _to_microbatches(target, cfg.microbatches)
                 .astype(jnp.float32)) ** 2
            )

        loss, grads = jax.value_and_grad(local_loss)(params)
        denom = float(np.prod(x.shape))
        new_params = jax.tree.map(
            lambda p, g: (p.astype(jnp.float32)
                          - lr * g / denom).astype(p.dtype),
            params, grads,
        )
        return new_params, loss / denom

    sm = jax.shard_map(
        step, mesh=mesh,
        in_specs=(pp_param_specs(mesh), P(), P()),
        out_specs=(pp_param_specs(mesh), P()),
    )
    return jax.jit(sm)


def pipeline_reference(params: Params, x, cfg: PipelineConfig,
                       block_fn: Callable = mlp_block):
    """Single-device oracle: stages applied sequentially, no pipeline."""
    y = x
    for s in range(cfg.stages):
        stage = {k: v[s:s + 1] for k, v in params.items()}
        y = block_fn(stage, y)
    return y


def place_pipeline_params(params: Params, mesh: Mesh) -> Params:
    specs = pp_param_specs(mesh)
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}
