"""Flagship train steps: SGD, LM cross-entropy, optax.

Split from flagship.py (round 2); see :mod:`tpu_p2p.models.flagship`
for the model overview. Each builder returns one jitted step whose
gradient reductions are implicit in ``shard_map`` autodiff; the manual
1F1B executor lives in :mod:`tpu_p2p.models.flagship_1f1b`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_p2p.parallel import collectives as C
from tpu_p2p.models.flagship_config import (
    FlagshipConfig,
    _data_axes,
    _mesh_axes,
)
from tpu_p2p.models.flagship_forward import (
    _forward_local,
    _fsdp_prepare,
    _lm_logits_local,
)
from tpu_p2p.models.flagship_params import (
    Params,
    _fsdp_plan,
    _lm_token_spec,
    flagship_data_spec,
    flagship_param_specs,
)


def _sgd_update(params: Params, grads, lr: float, denom: float):
    """`p - lr*g/denom` elementwise in f32, cast back to each param's
    dtype — the one SGD update shared by every train-step flavor."""
    return jax.tree.map(
        lambda p, g: (p.astype(jnp.float32)
                      - lr * g / denom).astype(p.dtype),
        params, grads,
    )


def _reject_zb_schedule(cfg: FlagshipConfig) -> None:
    """The GPipe steps differentiate *through* the schedule scan —
    autodiff owns their backward, so there is no dB/dW tick to split;
    a ``pp_schedule="zb"`` run here would silently time the autodiff
    baseline while its logs claim zero-bubble (the strict-knob class
    every overlap validation guards). The supported route is the
    tick-IR executor
    (:func:`tpu_p2p.models.flagship_1f1b.make_flagship_train_step_1f1b`,
    which lowers every schedule — fused, zb, switch — through
    ``tpu_p2p.models.schedule.lower()``; the zb program runs the
    jaxpr-partitioned ZB-H1 weight split of
    :mod:`tpu_p2p.models.zb_split`). ``tick_lowering="switch"`` is
    rejected here for the same reason: the cost-proportional dispatch
    is a property of the IR executor's tick tables — the GPipe scan
    is a masked schedule autodiff owns, and a switch label on it
    would silently time the masked baseline."""
    if cfg.pp_schedule == "zb":
        raise ValueError(
            "pp_schedule='zb' runs on the switch-lowered tick-IR "
            "executor (make_flagship_train_step_1f1b, which compiles "
            "zb through schedule.lower() with the ZB-H1 weight "
            "split); the GPipe autodiff steps have no backward ticks "
            "to split"
        )
    if cfg.tick_lowering != "masked":
        raise ValueError(
            f"tick_lowering={cfg.tick_lowering!r} runs on the tick-IR "
            "executor (make_flagship_train_step_1f1b, which lowers "
            "every schedule through schedule.lower()); the GPipe "
            "autodiff steps run a masked scan with no per-rank tick "
            "timeline to dispatch over"
        )


def make_flagship_grad_fn(mesh: Mesh, cfg: FlagshipConfig):
    """Jitted ``(params, x, target) → (grads, loss)`` over the mesh.

    Loss is the global sum of squared error; gradient reductions are
    implicit in ``shard_map`` autodiff (see
    :mod:`tpu_p2p.models.ring_transformer` for the accounting). Grads
    come back sharded exactly like the params, so any optimizer's
    elementwise update runs shard-local under ``jit``.
    """
    _reject_zb_schedule(cfg)
    axes = _mesh_axes(mesh)
    plan = _fsdp_plan(mesh, cfg)
    specs = flagship_param_specs(mesh, cfg)

    def gstep(params, x, target):
        def local_loss(p):
            # ZeRO gather-on-use sits inside the differentiated
            # function: its transpose is the gradient psum_scatter, so
            # grads come back dp-sharded like the params. Under
            # cfg.overlap="prefetch" the gathers move into the
            # per-layer loop (double buffer) and their transposes
            # become per-stage reduce-scatters interleaved with the
            # backward's compute (docs/fsdp_overlap.md).
            p, prefetch = _fsdp_prepare(p, cfg, plan)
            out = _forward_local(p, x, cfg, axes, prefetch=prefetch)
            return jnp.sum(
                (out.astype(jnp.float32) - target.astype(jnp.float32)) ** 2
            )

        loss, grads = jax.value_and_grad(local_loss)(params)
        # Sum the partial losses over every data-sharded axis; pp/tp
        # replicas are typed replicated and count once.
        data_axes = _data_axes(axes)
        if data_axes:
            loss = C.psum(loss, data_axes, label="loss_allreduce")
        return grads, loss

    sm = jax.shard_map(
        gstep, mesh=mesh,
        in_specs=(specs, flagship_data_spec(mesh), flagship_data_spec(mesh)),
        out_specs=(specs, P()),
    )
    return jax.jit(sm)


def make_flagship_train_step(mesh: Mesh, cfg: FlagshipConfig,
                             lr: float = 1e-2, donate: bool = False):
    """One jitted SGD step: forward, backward, update.

    ``donate=True`` donates the incoming params to the step so XLA
    updates them in place (halves param HBM traffic and peak param
    memory) — the caller must then treat the passed params as consumed
    (``params, loss = step(params, ...)``) and never reuse the old
    reference, so it is opt-in.
    """
    grad_fn = make_flagship_grad_fn(mesh, cfg)
    n_out = cfg.batch * cfg.seq * cfg.model_dim

    def step(params, x, target):
        grads, loss = grad_fn(params, x, target)
        return _sgd_update(params, grads, lr, n_out), loss / n_out

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_flagship_lm_grad_fn(mesh: Mesh, cfg: FlagshipConfig):
    """Jitted ``(params, tokens, targets) → (grads, summed CE)`` —
    the LM twin of :func:`make_flagship_grad_fn` (same contract: raw
    global-sum loss and grads; step builders own the normalization)."""
    if not cfg.vocab:
        raise ValueError("cfg.vocab must be > 0 for the LM step")
    _reject_zb_schedule(cfg)
    axes = _mesh_axes(mesh)
    plan = _fsdp_plan(mesh, cfg)
    specs = flagship_param_specs(mesh, cfg)

    def gstep(params, tokens, targets):
        def local_loss(p):
            pf, prefetch = _fsdp_prepare(p, cfg, plan)
            logits = _lm_logits_local(pf, tokens, cfg, axes,
                                      prefetch=prefetch)
            # CE via logsumexp rather than materializing
            # log_softmax's full [B, T, V] tensor: sum(nll) =
            # sum(logsumexp(logits)) - sum(logits[target]) exactly
            # (same max-shifted f32 math), and XLA fuses the rowwise
            # reduction without a second vocab-sized intermediate —
            # at production vocab (32k) that intermediate is GBs.
            m = jax.lax.stop_gradient(
                jnp.max(logits, axis=-1, keepdims=True)
            )
            lse = (m[..., 0]
                   + jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1)))
            tgt = jnp.take_along_axis(logits, targets[..., None],
                                      axis=-1)[..., 0]
            return jnp.sum(lse - tgt)

        loss, grads = jax.value_and_grad(local_loss)(params)
        data_axes = _data_axes(axes)
        if data_axes:
            loss = C.psum(loss, data_axes, label="loss_allreduce")
        return grads, loss

    tok_spec = _lm_token_spec(mesh)
    sm = jax.shard_map(
        gstep, mesh=mesh,
        in_specs=(specs, tok_spec, tok_spec),
        out_specs=(specs, P()),
    )
    return jax.jit(sm)


def make_flagship_lm_train_step(mesh: Mesh, cfg: FlagshipConfig,
                                lr: float = 1e-2, donate: bool = False):
    """One jitted SGD step on next-token cross-entropy.

    ``(params, tokens [B, T], targets [B, T]) → (params, mean CE)``
    (the caller shifts targets). Gradient reductions are implicit in
    shard_map autodiff, exactly as in the regression step. ``donate``
    as in :func:`make_flagship_train_step` (params updated in place;
    callers must reassign).
    """
    grad_fn = make_flagship_lm_grad_fn(mesh, cfg)
    n_tok = cfg.batch * cfg.seq

    def step(params, tokens, targets):
        grads, loss = grad_fn(params, tokens, targets)
        return _sgd_update(params, grads, lr, n_tok), loss / n_tok

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_flagship_optax_step(mesh: Mesh, cfg: FlagshipConfig, tx,
                             lm: bool = False, donate: bool = False):
    """One jitted step under any optax ``GradientTransformation``.

    ``(params, opt_state, x, target) → (params, opt_state, loss)``.
    The optimizer math is plain elementwise jit outside the shard_map:
    XLA propagates the param/grad shardings into the update, so mu/nu
    moments shard exactly like their params. Initialize with
    :func:`init_optimizer`. ``lm=True`` trains next-token CE on token
    batches (``cfg.vocab > 0``); ``donate`` donates params AND opt
    state (callers must reassign both).
    """
    import optax

    if lm:
        grad_fn = make_flagship_lm_grad_fn(mesh, cfg)
        n_out = cfg.batch * cfg.seq
    else:
        grad_fn = make_flagship_grad_fn(mesh, cfg)
        n_out = cfg.batch * cfg.seq * cfg.model_dim

    def step(params, opt_state, x, target):
        grads, loss = grad_fn(params, x, target)
        grads = jax.tree.map(lambda g: g / n_out, grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss / n_out

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def init_optimizer(tx, params: Params):
    """``tx.init`` with the optimizer state explicitly sharded like the
    params: per-param moments (mu/nu/trace…) get that param's sharding,
    everything else (step counts) is replicated. jit alone does NOT do
    this — sharding propagation through a broadcast-of-zeros picks a
    default placement, which would silently replicate ZeRO moments.

    Leaves are matched to params by tree path: optax state subtrees
    mirror the params dict, so the innermost dict key naming a param
    (with matching shape) identifies its sharding.
    """
    shardings = {k: getattr(v, "sharding", None) for k, v in params.items()}
    if any(not isinstance(s, NamedSharding) for s in shardings.values()):
        return jax.jit(tx.init)(params)  # unplaced params: plain init
    mesh = next(iter(shardings.values())).mesh
    replicated = NamedSharding(mesh, P())

    def leaf_sharding(path, leaf):
        for entry in reversed(path):
            name = getattr(entry, "key", None)
            if name in params and leaf.shape == params[name].shape:
                return shardings[name]
        return replicated

    shapes = jax.eval_shape(tx.init, params)
    out_shardings = jax.tree_util.tree_map_with_path(leaf_sharding, shapes)
    return jax.jit(tx.init, out_shardings=out_shardings)(params)
