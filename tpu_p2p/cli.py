"""CLI — L0'/config entry point (SURVEY.md §7 item 6).

The reference's launch contract is ``mpirun -n NUM_PROCESS p2p_matrix``
with zero program flags (``/root/reference/README.md:5``;
``p2p_matrix.cc:105`` passes argv only to MPI). On TPU the launcher
disappears — JAX enumerates the slice's devices itself — and the
BASELINE.json configs (size sweeps, patterns, mesh axes) require real
flags, with defaults reproducing the reference's constants
(32 MiB / 128 iters / int8 — ``p2p_matrix.cc:124,132,158``).

Run: ``python -m tpu_p2p [flags]`` or ``make run ARGS="..."``.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

from tpu_p2p.config import (
    BenchConfig,
    DIRECTIONS,
    ISOLATIONS,
    MODES,
    PATTERNS,
    PP_SCHEDULES,
    TICK_LOWERINGS,
    TRANSPORTS,
    parse_size,
    parse_sweep,
)
from tpu_p2p.utils.errors import fail_fast


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_p2p",
        description=(
            "TPU-native interconnect microbenchmarks: all-pairs P2P "
            "bandwidth matrices (the reference workload), ring / "
            "all_to_all / 2D-torus collectives, small-message latency, "
            "and a ring-attention transport workload."
        ),
    )
    p.add_argument("--pattern", choices=PATTERNS, default="pairwise",
                   help="workload to run (default: the reference's all-pairs matrix)")
    p.add_argument("--msg-size", default=None, metavar="SIZE",
                   help="payload per message, e.g. 4KiB, 32MiB, 1GiB "
                        "(default: 32MiB per the reference; latency/loopback "
                        "default to their metric sizes 8B/4KiB)")
    p.add_argument("--sweep", default=None, metavar="LO:HI|A,B,...",
                   help="message-size sweep: power-of-two range '1KiB:1GiB' or explicit list")
    p.add_argument("--iters", type=int, default=128,
                   help="messages per measured cell (reference: 128)")
    p.add_argument("--warmup", type=int, default=1,
                   help="untimed warm-up calls per cell; excludes XLA compile (reference: 0)")
    p.add_argument("--dtype", default="int8", help="payload dtype (reference: int8)")
    p.add_argument("--direction", choices=DIRECTIONS, default="both",
                   help="pairwise sweeps to run (reference runs uni then bi)")
    p.add_argument("--mode", choices=MODES, default="serialized",
                   help="serialized = one message in flight (reference semantics); "
                        "fused = device-chained hops, no host dispatch")
    p.add_argument("--transport", choices=TRANSPORTS, default="xla",
                   help="permute transport for pairwise/latency/loopback "
                        "pairs: xla = CollectivePermute (default); "
                        "pallas_dma = raw async remote copies "
                        "(make_async_remote_copy Pallas kernels — the "
                        "sub-XLA backend; interpret-mode on non-TPU, "
                        "gated by a capability probe)")
    p.add_argument("--isolation", choices=ISOLATIONS, default="full",
                   help="full = one N-device program per pair; submesh = 2-device mesh per pair")
    p.add_argument("--num-devices", type=int, default=None,
                   help="use only the first N devices")
    p.add_argument("--mesh-shape", default=None, metavar="AxB",
                   help="2D mesh, e.g. 4x2 (required for torus2d)")
    p.add_argument("--hybrid", action="store_true",
                   help="multi-slice jobs: build a ('dcn', 'd') mesh whose "
                        "leading axis crosses DCN (use with --pattern "
                        "torus2d to measure ICI vs DCN separately)")
    p.add_argument("--fused-repeats", type=int, default=3,
                   help="timed chain executions in fused mode")
    p.add_argument("--timeout", type=float, default=None, metavar="SECONDS",
                   help="per-transfer watchdog; wedged cells report NaN instead of hanging")
    p.add_argument("--check", action="store_true",
                   help="verify payload contents after transfer (rank-tagged patterns)")
    p.add_argument("--jsonl", default=None, metavar="PATH",
                   help="append per-cell JSONL records (machine-readable twin of the matrix)")
    p.add_argument("--resume", action="store_true",
                   help="skip cells already recorded in --jsonl")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run")
    p.add_argument("--validate-timing", action="store_true",
                   help="after the run, cross-check the host differential "
                        "slope against XLA's device-trace timeline on a "
                        "canonical chain (loopback on 1 device, ring "
                        "ppermute otherwise); MISMATCH exits nonzero")
    p.add_argument("--flash", action="store_true",
                   help="ring_attention: use the Pallas flash kernel for the "
                        "block-accumulate step")
    p.add_argument("--attn-window", type=int, default=0, metavar="W",
                   help="ring/ulysses_attention: sliding-window attention; "
                        "windowed contiguous rings drop provably-dead hops")
    p.add_argument("--zero-dp", action="store_true",
                   help="flagship_step: ZeRO-3/FSDP param sharding over "
                        "the dp axis")
    p.add_argument("--overlap", choices=("none", "prefetch"),
                   default="none",
                   help="flagship_step + --zero-dp: FSDP gather schedule "
                        "(prefetch = double-buffered per-layer all-gather "
                        "overlapped with compute)")
    p.add_argument("--tp-overlap", choices=("none", "ring"),
                   default="none",
                   help="flagship_step: Megatron tp-join schedule (ring "
                        "= ppermute collective-matmul decomposition, "
                        "per-chunk transfers overlapped with the matmuls;"
                        " no-op at tp=1)")
    p.add_argument("--ep-overlap", choices=("none", "ring"),
                   default="none",
                   help="flagship_step: MoE expert-parallel reshard "
                        "schedule (ring = shift-by-s ppermute "
                        "decomposition of the dispatch/combine "
                        "all_to_alls, expert FFN einsums overlapped "
                        "with the hops; no-op at ep=1)")
    p.add_argument("--pp-overlap", choices=("none", "wave"),
                   default="none",
                   help="flagship_step: pipeline stage-hop schedule "
                        "(wave = the per-tick ppermute split into "
                        "token-chunk waves, each chunk's transfer in "
                        "flight under the remaining tick compute; "
                        "no-op at pp=1)")
    p.add_argument("--pp-schedule", choices=PP_SCHEDULES,
                   default="1f1b",
                   help="flagship_step: pipeline tick schedule under "
                        "the tick-IR executor (zb = zero-bubble "
                        "ZB-H1 weight split — GEMM-only dW ticks "
                        "fill the 1F1B bubbles, step bitwise vs "
                        "1f1b; routes the workload through the "
                        "tick-IR 1F1B executor)")
    p.add_argument("--tick-lowering", choices=TICK_LOWERINGS,
                   default="masked",
                   help="flagship_step: tick lowering for the IR "
                        "executor's compiled programs (switch = "
                        "cost-proportional per-rank lax.switch "
                        "dispatch — idle ranks genuinely idle, step "
                        "bitwise vs masked; routes the workload "
                        "through the manual executor even under "
                        "--pp-schedule 1f1b)")
    p.add_argument("--cpu-mesh", type=int, default=None, metavar="N",
                   help="testing: force CPU platform with N simulated devices")
    p.add_argument("--list-devices", action="store_true",
                   help="print the validated device/topology table and exit")
    return p


def config_from_args(args: argparse.Namespace) -> BenchConfig:
    mesh_shape = None
    if args.mesh_shape:
        try:
            mesh_shape = tuple(int(d) for d in args.mesh_shape.lower().split("x"))
        except ValueError:
            raise SystemExit(
                f"--mesh-shape must look like 4x2, got {args.mesh_shape!r}"
            )
    return BenchConfig(
        pattern=args.pattern,
        msg_size=parse_size(args.msg_size) if args.msg_size is not None else None,
        iters=args.iters,
        warmup=args.warmup,
        dtype=args.dtype,
        direction=args.direction,
        mode=args.mode,
        isolation=args.isolation,
        transport=args.transport,
        num_devices=args.num_devices,
        mesh_shape=mesh_shape,
        sweep=parse_sweep(args.sweep) if args.sweep else None,
        fused_repeats=args.fused_repeats,
        timeout_s=args.timeout,
        check=args.check,
        jsonl=args.jsonl,
        resume=args.resume,
        profile_dir=args.profile_dir,
        use_flash=args.flash,
        attn_window=args.attn_window,
        zero_dp=args.zero_dp,
        overlap=args.overlap,
        tp_overlap=args.tp_overlap,
        ep_overlap=args.ep_overlap,
        pp_overlap=args.pp_overlap,
        pp_schedule=args.pp_schedule,
        tick_lowering=args.tick_lowering,
    )


def _force_cpu_mesh(n: int) -> None:
    """Testing backdoor: N simulated devices on the host platform.

    Note: this process's sitecustomize may already have imported jax
    with a TPU plugin bound, so the env-var route alone is not enough —
    the config update must run before any backend instantiation.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={n}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")


def _print_devices(rt) -> None:
    print(f"{rt.num_devices} devices on {rt.placement.num_hosts} host(s), "
          f"{rt.placement.devices_per_host} per host; mesh axes "
          f"{dict(zip(rt.mesh.axis_names, rt.mesh.devices.shape))}")
    for i, d in enumerate(rt.devices):
        coords = getattr(d, "coords", None)
        extra = f" coords={coords}" if coords is not None else ""
        print(f"  [{i}] {d.device_kind} host={rt.placement.host_of[i]} "
              f"local={rt.placement.local_ids[i]}{extra}")
    if rt.torus is not None:
        print(f"  torus dims: {rt.torus.dims}")


def _validate_timing(rt, cfg) -> int:
    """SURVEY.md §7(b): cross-check host differential timing against
    XLA's device-event timeline (the ``cudaEvent_t`` analogue) on one
    canonical chain for this mesh. Prints one diagnostic line; a
    MISMATCH (device track present but slopes disagree beyond 2x)
    exits 1 so CI can gate on it.
    """
    import tempfile

    from tpu_p2p.parallel import collectives as C
    from tpu_p2p.utils import timing
    from tpu_p2p.utils.profiling import validate_differential

    cache = C.CollectiveCache()
    import numpy as np

    msg = cfg.msg_size or 4 * 1024 * 1024
    x = C.make_payload(rt.mesh, msg, dtype=np.dtype(cfg.dtype))
    n = rt.num_devices
    if n >= 2:
        axis = rt.mesh.axis_names[0]
        edges = C.ring_edges(n)
        chain_of = lambda k: cache.permute_chain(rt.mesh, axis, edges, k)  # noqa: E731
        label = f"ring ppermute x{n}"
    else:
        chain_of = lambda k: cache.loopback_chain(rt.mesh, k)  # noqa: E731
        label = "loopback rewrite"
    with tempfile.TemporaryDirectory(prefix="tpu_p2p_vt_") as td:
        # 128-op chains: the long-short delta must clear relay jitter
        # (measured ±5 ms per call some sessions) for the host slope
        # to be meaningful at all; at 4 MiB+ payloads 112 extra ops is
        # tens of ms of real device time.
        v = validate_differential(chain_of, x, max(128, cfg.iters),
                                  trace_dir=td, timing=timing, repeats=5)
    # Every rank validates (each has its own host clock and local
    # trace), but only the printer rank reports — like all other
    # stdout (advisor round-2 #4). The nonzero exit stays per-rank:
    # any rank's MISMATCH fails its process, which the launcher sees.
    import jax

    if jax.process_index() == 0:
        print(f"# {v.describe()}  [{label}, {msg} B]")
    return 0 if v.ok in (True, None) else 1


def _assert_resume_agreement(done: dict) -> None:
    """Fail fast when ranks disagree on the resumed done-cell set.

    JSONL records are written by the printer rank only, so ``--resume``
    on a multi-host run requires the log on a filesystem every rank
    reads (workloads/base.py docstring). If ranks instead load
    different sets — e.g. per-host local paths where non-zero ranks
    see an empty file — each skips different cells and the job
    deadlocks at a per-cell barrier. Comparing a digest of the set
    across ranks turns that silent hang into an immediate, explained
    error (advisor round-2 #3). Single-process: no-op.
    """
    import jax

    if jax.process_count() <= 1:
        return
    import hashlib

    import numpy as np
    from jax.experimental import multihost_utils

    digest = hashlib.sha256(
        repr(sorted(map(repr, done))).encode()
    ).digest()[:8]
    mine = np.frombuffer(digest, dtype=np.uint8)
    try:
        multihost_utils.assert_equal(
            mine,
            "ranks disagree on the --resume done-cell set; put the "
            "--jsonl log on a filesystem shared by every process",
        )
    except AssertionError:
        raise
    except Exception as e:  # pragma: no cover - backend-specific raise
        raise RuntimeError(
            "ranks disagree on the --resume done-cell set; put the "
            "--jsonl log on a filesystem shared by every process"
        ) from e


def main(argv: Optional[Sequence[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "obs":
        # ``python -m tpu_p2p obs`` — the observability report +
        # bench regression gate (tpu_p2p/obs/regress.py). Dispatched
        # before the benchmark argparse: the subcommand has its own
        # flag set and exit-code contract (nonzero on regression).
        from tpu_p2p.obs.regress import main as obs_main

        return obs_main(list(argv[1:]))
    if argv and argv[0] == "serve":
        # ``python -m tpu_p2p serve`` — the serving engine smoke:
        # paged KV cache + continuous batching over a synthetic
        # Poisson request trace (tpu_p2p/serve/, docs/serving.md).
        # Dispatched like obs: its own flag set.
        from tpu_p2p.serve.engine import main as serve_main

        return serve_main(list(argv[1:]))
    if argv and argv[0] == "topo":
        # ``python -m tpu_p2p topo`` — the topology model report +
        # placement recommendations, and (--smoke) the graded
        # injected-throttle check (tpu_p2p/topo/, docs/topology.md).
        # Dispatched like obs/serve: its own flag set and exit-code
        # contract (nonzero when the smoke fails to route around an
        # injected degraded link).
        from tpu_p2p.topo.cli import main as topo_main

        return topo_main(list(argv[1:]))
    if argv and argv[0] == "zb":
        # ``python -m tpu_p2p zb`` — the graded zero-bubble schedule
        # smoke (tpu_p2p/models/zb_smoke.py, docs/schedule_ir.md):
        # fused production step vs the zb route under the switch tick
        # lowering, bitwise loss parity plus the wall-clock grade.
        # Dispatched like obs/serve/topo: its own flag set and
        # exit-code contract (nonzero unless zb beats the fused step).
        from tpu_p2p.models.zb_smoke import main as zb_main

        return zb_main(list(argv[1:]))
    if argv and argv[0] == "train":
        # ``python -m tpu_p2p train`` — the training loop
        # (tpu_p2p/train.py: durable checkpoint/resume, --heal,
        # --supervise). Dispatched like obs/serve so the golden
        # harness (and users) reach every entry point through ONE
        # program; ``python -m tpu_p2p.train`` stays equivalent.
        from tpu_p2p.train import main as train_main

        return train_main(list(argv[1:]))
    args = build_parser().parse_args(argv)
    try:
        if args.cpu_mesh:
            _force_cpu_mesh(args.cpu_mesh)
        cfg = config_from_args(args)

        # Imports deferred past _force_cpu_mesh so the platform switch
        # precedes backend instantiation.
        from tpu_p2p.parallel.runtime import make_hybrid_runtime, make_runtime
        from tpu_p2p.utils.report import JsonlWriter, load_done_cells
        from tpu_p2p.workloads import WORKLOADS  # registers all patterns

        if args.hybrid:
            if cfg.mesh_shape is not None:
                raise SystemExit(
                    "--hybrid builds its own ('dcn', 'd') mesh; "
                    "drop --mesh-shape"
                )
            if cfg.pattern != "torus2d":
                raise SystemExit(
                    "--hybrid currently supports --pattern torus2d (per-axis "
                    f"rings separate DCN from ICI); {cfg.pattern!r} assumes "
                    "a flat 1D mesh"
                )
            rt = make_hybrid_runtime(num_devices=cfg.num_devices)
        else:
            rt = make_runtime(
                num_devices=cfg.num_devices, mesh_shape=cfg.mesh_shape
            )
        if args.list_devices:
            _print_devices(rt)
            return 0
        run = WORKLOADS.get(cfg.pattern)
        if run is None:
            raise SystemExit(f"pattern {cfg.pattern!r} is not implemented yet")

        from tpu_p2p.workloads.base import WorkloadContext

        done = load_done_cells(cfg.jsonl) if cfg.resume else {}
        if cfg.resume:
            _assert_resume_agreement(done)
        ctx = WorkloadContext(
            rt=rt,
            cfg=cfg,
            jsonl=JsonlWriter(cfg.jsonl) if cfg.jsonl else None,
            done=done,
        )
        try:
            if cfg.profile_dir:
                import jax

                with jax.profiler.trace(cfg.profile_dir):
                    run(ctx)
            else:
                run(ctx)
        finally:
            if ctx.jsonl is not None:
                ctx.jsonl.close()
        if args.validate_timing:
            return _validate_timing(rt, cfg)
        return 0
    except KeyboardInterrupt:
        print("interrupted", file=sys.stderr)
        return 130
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — single fail-fast handler (L8)
        return fail_fast(e)
