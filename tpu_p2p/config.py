"""Config / flag system.

The reference has none — zero CLI args and zero env reads; every
parameter is a compile-time constant: ``msg_size = 32*1024*1024``
(``/root/reference/p2p_matrix.cc:124``), ``count = 128`` (``:132``),
dtype ``ncclInt8`` (``:158``), world size via ``mpirun -n``
(``/root/reference/README.md:5``). SURVEY.md §5 mandates a real flag
system for the BASELINE.json configs (message sweeps, patterns, mesh
axes) with defaults that reproduce the reference's constants exactly.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, fields
from typing import Optional, Tuple

# Reference constants (the defaults contract):
REF_MSG_SIZE = 32 * 1024 * 1024  # p2p_matrix.cc:124
REF_ITERS = 128  # p2p_matrix.cc:132
REF_DTYPE = "int8"  # p2p_matrix.cc:158 (ncclInt8)

_SIZE_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([KMGT]i?)?B?\s*$", re.IGNORECASE)
_UNIT = {
    None: 1,
    "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12,
    "KI": 2**10, "MI": 2**20, "GI": 2**30, "TI": 2**40,
}


def parse_size(text) -> int:
    """Parse ``'32MiB'``, ``'4KB'``, ``'1G'``, ``'8'`` → bytes."""
    if isinstance(text, int):
        return text
    m = _SIZE_RE.match(str(text))
    if not m:
        raise ValueError(f"unparseable size {text!r}")
    num, unit = m.groups()
    mult = _UNIT[unit.upper() if unit else None]
    return int(float(num) * mult)


def format_size(nbytes: int) -> str:
    for unit, mult in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if nbytes % mult == 0 and nbytes >= mult:
            return f"{nbytes // mult}{unit}"
    return f"{nbytes}B"


def parse_edge(text: str) -> Tuple[int, int]:
    """Parse ``'0:1'`` → the directed edge ``(0, 1)`` — the CLI
    spelling of a ``FaultPlan.degrade_edge``
    (``train.py --fault-degrade-edge``, docs/health.md)."""
    parts = str(text).split(":")
    try:
        src, dst = (int(p) for p in parts)
        if src < 0 or dst < 0:
            raise ValueError("negative device index")
    except ValueError:
        raise ValueError(
            f"unparseable edge {text!r}; expected SRC:DST with "
            "non-negative device indices, e.g. 0:1"
        ) from None
    return src, dst


def parse_range(text: str) -> Tuple[int, int]:
    """Parse ``'4:12'`` → the inclusive integer range ``(4, 12)`` —
    the CLI spelling of the serve trace's prompt/output length ranges
    (``python -m tpu_p2p serve --prompt-len``, docs/serving.md)."""
    parts = str(text).split(":")
    try:
        lo, hi = (int(p) for p in parts)
        if lo < 1 or hi < lo:
            raise ValueError("empty or non-positive range")
    except ValueError:
        raise ValueError(
            f"unparseable range {text!r}; expected LO:HI with "
            "1 <= LO <= HI, e.g. 4:12"
        ) from None
    return lo, hi


def parse_sweep(text: str) -> Tuple[int, ...]:
    """``'1KiB:1GiB'`` → powers-of-two sweep; ``'4KB,32MiB'`` → list."""
    if ":" in text:
        lo_s, hi_s = text.split(":", 1)
        lo, hi = parse_size(lo_s), parse_size(hi_s)
        sizes = []
        s = lo
        while s <= hi:
            sizes.append(s)
            s *= 2
        return tuple(sizes)
    return tuple(parse_size(p) for p in text.split(","))


CKPT_KEEP = 3
# Checkpoint retention (round 17, docs/checkpoint_durability.md):
# how many published ``gen-<step>/`` generations
# ``utils/checkpoint.save_generation`` keeps after each atomic
# publish. ONE definition governs the save default and the
# ``train.py --ckpt-keep`` CLI default alike (the PP_SCHEDULES
# single-source rule). Three generations is the smallest ladder that
# still recovers when the newest generation is damaged AND the
# fallback one is mid-overwrite: the verifying loader
# (checkpoint.load_latest) walks newest → oldest and settles on the
# first intact one.

PATTERNS = (
    "pairwise",      # all-pairs matrix — the reference program itself
    "loopback",      # self-edge / same-host copy (BASELINE configs[0])
    "ring",          # shift-by-1 ppermute (configs[2])
    "all_to_all",    # configs[3]
    "torus2d",       # both mesh axes (configs[4])
    "latency",       # 8B p50 send/recv latency (BASELINE metric)
    "allreduce",     # psum busbw — the DP gradient transport
    "reduce_scatter",  # psum_scatter busbw — the ZeRO gradient transport
    "all_gather",    # tiled all_gather busbw — the ZeRO parameter transport
    "ring_attention",  # flagship SP workload over the same transport
    "ulysses_attention",  # all_to_all SP counterpart (configs[3] transport)
    "flagship_step",  # the composite 5-axis train-step benchmark
)

MODES = ("serialized", "fused", "differential", "device")
# SURVEY.md §7 hard part (c):
# differential = two-chain-length slope, cancels all constant per-call
# overhead (the only trustworthy HOST mode on relayed PJRT platforms);
# device = the differential slope read off XLA's own device timeline
# (jax.profiler trace — the cudaEvent_t analogue, BASELINE.json north
# star), immune to host/relay jitter entirely; falls back to the host
# slope on platforms recording no device track (CPU), and each cell
# records which source it published.
ISOLATIONS = ("full", "submesh")  # SURVEY.md §7 hard part (a)
DIRECTIONS = ("uni", "bi", "both")
TRANSPORTS = ("xla", "pallas_dma")
PP_SCHEDULES = ("1f1b", "zb")
TICK_LOWERINGS = ("masked", "switch")
# Programs the tick flight recorder can compile and profile
# (tpu_p2p/obs/tickprof.py `obs trace`): the two production backward
# schedules plus the forward-only GPipe program (whose recorder
# stamps ride the differentiated forward scan). ONE definition
# governs the `obs trace` CLI choices and the bench's measured-bubble
# arm, the PP_SCHEDULES single-source rule.
TRACE_SCHEDULES = ("zb", "1f1b", "gpipe")
# Manual-executor tick lowerings (tpu_p2p/models/schedule.py lower()):
# "masked" = the legacy masked-SPMD execution — every rank runs every
# tick's full compute body and discards idle work through
# where-masks (bitwise the pre-IR executors, the default); "switch" =
# the cost-proportional lowering — each rank's tick body dispatches
# through ONE lax.switch over the program's compact op table
# (fwd / bwd / bwd_input / bwd_weight / no-op), so a rank whose tick
# is idle pays only the branch select and the collective hop it
# participates in. The two lowerings are BITWISE equal in value
# (tests/test_schedule.py); switch is what lets the zero-bubble
# schedule's analytic win cash out as wall clock
# (docs/schedule_ir.md). ONE definition governs the CLI choices,
# BenchConfig, and FlagshipConfig validation alike, like
# PP_SCHEDULES.
# Manual-executor pipeline tick schedules (tpu_p2p/models/schedule.py):
# "1f1b" = the fused-backward 1F1B/interleaved program (the default —
# bitwise the pre-IR executors); "zb" = the ZB-H1-style zero-bubble
# split (backward decomposed into input-grad ticks on the critical
# path and weight-grad ticks filling the warmup/drain bubbles; step
# stays bitwise vs "1f1b", the schedule just idles less —
# docs/schedule_ir.md). ONE definition governs the CLI choices,
# BenchConfig, and FlagshipConfig validation alike, like TRANSPORTS.
# xla = CollectivePermute programs (the default — every number before
# round 11 was measured over it); pallas_dma = raw async remote copies
# (pltpu.make_async_remote_copy kernels, tpu_p2p/parallel/pallas_dma.py)
# behind the runtime capability probe — the sub-XLA backend that
# strips the ~0.55 µs program-dispatch floor off the p2p matrix and
# latency workloads (docs/pallas_dma.md).


@dataclass
class BenchConfig:
    """Everything a run needs; defaults = the reference's constants."""

    pattern: str = "pairwise"
    # None = unset; bandwidth patterns then use the reference's 32 MiB
    # (via sizes()), while latency/loopback substitute their own metric
    # sizes. An explicit value is always honored verbatim.
    msg_size: Optional[int] = None
    iters: int = REF_ITERS
    warmup: int = 1  # deviation from reference (0 there): excludes XLA compile
    dtype: str = REF_DTYPE
    direction: str = "both"  # reference runs uni then bi (p2p_matrix.cc:141,196)
    mode: str = "serialized"  # reference semantics: one message in flight
    isolation: str = "full"
    num_devices: Optional[int] = None
    mesh_shape: Optional[Tuple[int, ...]] = None
    sweep: Optional[Tuple[int, ...]] = None  # message-size sweep (configs[1])
    fused_repeats: int = 3
    timeout_s: Optional[float] = None
    check: bool = False  # verify payload contents after transfer
    jsonl: Optional[str] = None  # structured twin of the stdout matrix
    resume: bool = False  # skip cells already present in jsonl
    seed: int = 0
    profile_dir: Optional[str] = None  # jax.profiler trace output
    use_flash: bool = False  # Pallas flash kernel on the SP attention
    # workloads (trainable everywhere since tpu_p2p.ops.ring_flash)
    attn_window: int = 0  # > 0: sliding-window attention on the SP
    # workloads — windowed contiguous rings also DROP dead hops
    # (tpu_p2p.ops.attention.live_ring_hops), which this surface makes
    # measurable as shipped bytes
    overlap: str = "none"  # flagship_step: FSDP parameter-gather
    # scheduling ("none" = bulk gather before the forward, "prefetch"
    # = double-buffered per-layer gather overlapped with compute);
    # mirrors FlagshipConfig.overlap, see tpu_p2p/parallel/fsdp.py.
    # Only meaningful with zero_dp and a dp axis; other patterns
    # ignore it.
    zero_dp: bool = False  # flagship_step: ZeRO-3/FSDP param sharding
    # over the dp axis (FlagshipConfig.zero_dp)
    tp_overlap: str = "none"  # flagship_step: Megatron tp-join
    # scheduling ("none" = blocking psum joins, "ring" = ppermute
    # collective-matmul decomposition overlapping per-chunk transfers
    # with the MXU); mirrors FlagshipConfig.tp_overlap, see
    # tpu_p2p/parallel/collectives.py ring_allgather_matmul /
    # matmul_ring_reducescatter. No-op at tp=1; other patterns
    # ignore it.
    ep_overlap: str = "none"  # flagship_step: MoE expert-parallel
    # reshard scheduling ("none" = blocking tiled all_to_alls for
    # dispatch/combine, "ring" = shift-by-s ppermute decomposition
    # with the expert FFN einsums overlapping the hops); mirrors
    # FlagshipConfig.ep_overlap, see tpu_p2p/parallel/collectives.py
    # ring_all_to_all_matmul / matmul_ring_all_to_all. No-op at ep=1;
    # other patterns ignore it.
    transport: str = "xla"  # permute-family transport backend for the
    # pairwise / latency / loopback-pair workloads: "xla" =
    # CollectivePermute (default, bitwise the pre-knob behavior),
    # "pallas_dma" = raw async-remote-copy Pallas kernels
    # (collectives.dma_ppermute; gated by runtime.pallas_dma_supported,
    # a BackendError names the probe failure otherwise). Collective
    # patterns (allreduce &c) have no permute transport and ignore it.
    pp_overlap: str = "none"  # flagship_step: pipeline stage-hop
    # scheduling ("none" = one blocking ppermute per tick, "wave" =
    # the hop split into token-chunk waves, each chunk's transfer in
    # flight under the remaining tick compute); mirrors
    # FlagshipConfig.pp_overlap, see tpu_p2p/parallel/collectives.py
    # chunked_ppermute_compute. No-op at pp=1; other patterns
    # ignore it.
    pp_schedule: str = "1f1b"  # flagship_step: pipeline tick schedule
    # under the MANUAL executor ("zb" routes the step through
    # make_flagship_train_step_1f1b with the zero-bubble dB/dW-split
    # program — tpu_p2p/models/schedule.py compile_zb; "1f1b" keeps
    # the default GPipe-autodiff step). Mirrors
    # FlagshipConfig.pp_schedule; other patterns ignore it.
    tick_lowering: str = "masked"  # flagship_step: tick lowering for
    # the MANUAL executor's compiled programs ("switch" = the
    # cost-proportional lax.switch dispatch — idle ranks genuinely
    # idle; routes the workload through the IR executor even under
    # pp_schedule="1f1b"). Mirrors FlagshipConfig.tick_lowering, one
    # TICK_LOWERINGS definition; other patterns ignore it.

    def __post_init__(self) -> None:
        if self.pattern not in PATTERNS:
            raise ValueError(f"pattern {self.pattern!r} not in {PATTERNS}")
        if self.mode not in MODES:
            raise ValueError(f"mode {self.mode!r} not in {MODES}")
        if self.isolation not in ISOLATIONS:
            raise ValueError(f"isolation {self.isolation!r} not in {ISOLATIONS}")
        if self.direction not in DIRECTIONS:
            raise ValueError(f"direction {self.direction!r} not in {DIRECTIONS}")
        if self.iters <= 0:
            raise ValueError("iters must be positive")
        if self.attn_window < 0:
            raise ValueError(
                f"attn_window must be >= 0, got {self.attn_window}"
            )
        if self.overlap not in ("none", "prefetch"):
            raise ValueError(
                f"unknown overlap {self.overlap!r}; expected 'none' "
                "or 'prefetch'"
            )
        if self.tp_overlap not in ("none", "ring"):
            raise ValueError(
                f"unknown tp_overlap {self.tp_overlap!r}; expected "
                "'none' or 'ring'"
            )
        if self.ep_overlap not in ("none", "ring"):
            raise ValueError(
                f"unknown ep_overlap {self.ep_overlap!r}; expected "
                "'none' or 'ring'"
            )
        if self.pp_overlap not in ("none", "wave"):
            raise ValueError(
                f"unknown pp_overlap {self.pp_overlap!r}; expected "
                "'none' or 'wave'"
            )
        if self.pp_schedule not in PP_SCHEDULES:
            raise ValueError(
                f"unknown pp_schedule {self.pp_schedule!r}; expected "
                f"one of {PP_SCHEDULES}"
            )
        if self.tick_lowering not in TICK_LOWERINGS:
            raise ValueError(
                f"unknown tick_lowering {self.tick_lowering!r}; "
                f"expected one of {TICK_LOWERINGS}"
            )
        if self.transport not in TRANSPORTS:
            raise ValueError(
                f"unknown transport {self.transport!r}; expected one "
                f"of {TRANSPORTS}"
            )

    @property
    def window(self):
        """``attn_window`` in the ops-layer convention (0 → None) —
        the single translation point for the SP workloads."""
        return self.attn_window or None

    def sizes(self) -> Tuple[int, ...]:
        if self.sweep:
            return self.sweep
        return (self.msg_size if self.msg_size is not None else REF_MSG_SIZE,)

    def replace(self, **kw) -> "BenchConfig":
        d = {f.name: getattr(self, f.name) for f in fields(self)}
        d.update(kw)
        return BenchConfig(**d)


BATCHING = ("continuous", "static", "both")
# Serving-engine batching modes (docs/serving.md): continuous = slots
# refilled from the queue the step a sequence finishes; static = the
# run-to-completion baseline (the batch refills only when every slot
# drained — the A/B bench grades); both = run the A/B on one trace.

SERVE_STOPS = ("length", "eos")
# Serving stop rules (docs/serving_resilience.md): length = generate
# exactly max_new tokens (the default — schedules stay trivially
# length-driven); eos = seeded variable-length stopping, each
# generated token drawing a stop decision keyed on (seed, request_id,
# generation index) — value-free, so the dry schedule simulator and
# the device batcher agree bit for bit and replay stays exact.


@dataclass(frozen=True)
class ServeConfig:
    """Everything one serving run needs (tpu_p2p/serve/engine.py):
    the paged-cache geometry, the slot batch, and the synthetic
    trace. Mesh-dependent divisibility (slots / num_pages over the
    dp×ep shard count) is validated where the mesh exists — in the
    batcher/pool constructors."""

    slots: int = 8            # fixed-width slot batch
    page_len: int = 8         # tokens per KV page (multiple of 8 —
    # the band-write granularity, ops/kvcache.paged_rows_write)
    num_pages: int = 64       # global page-pool size (incl. each
    # shard's reserved trash page)
    max_blocks: int = 8       # page-table width = the attention
    # window in pages (max_blocks * page_len positions)
    chunk: int = 4            # prefill chunk width per step (1/2/4/8:
    # multi-token chunks must stay inside one 8-row write band)
    batching: str = "continuous"
    requests: int = 8         # synthetic trace length
    seed: int = 0
    rate: float = 1.0         # mean Poisson arrivals per scheduler step
    prompt_len: Tuple[int, int] = (4, 12)   # inclusive
    gen_len: Tuple[int, int] = (4, 8)       # inclusive
    vocab: int = 128
    dtype: str = "float32"
    # Resilience knobs (round 15, docs/serving_resilience.md) — all
    # default-off, preserving the round-13 behavior:
    queue_depth: int = 0      # bounded admission queue (0 =
    # unbounded); a submit against a full queue sheds immediately
    # with outcome "shed_admission"
    deadline_steps: int = 0   # admission deadline in scheduler steps
    # (0 = none): a queued request whose prefill has not started
    # within this many steps of enqueue sheds with "shed_deadline"
    stop: str = "length"      # stop rule, one of SERVE_STOPS
    eos_prob: float = 0.1     # stop="eos": per-token seeded stop
    # probability (geometric lengths capped by max_new)
    # Disaggregated prefill/decode (round 18,
    # docs/serving_disagg.md) — all default-off, preserving the
    # colocated engine byte for byte:
    disagg: bool = False      # partition the device mesh into a
    # tp-heavy prefill submesh and a replica-heavy decode submesh;
    # completed prefills migrate their KV pages across as explicit
    # instrumented p2p transfers (ledger kind="kv_migrate")
    prefill_tp: int = 0       # prefill submesh tp size == its device
    # count (the submesh is 1×tp by construction); 0 = auto, half the
    # devices. Validated like build_mesh where the devices exist —
    # serve/disagg.build_disagg_meshes.
    prefill_slots: int = 4    # prefill-side slot batch (chunked
    # prefill only; decode slots stay `slots`)
    prefill_pages: int = 0    # prefill-side page pool (one shard);
    # 0 = auto, sized by the engine to the worst-case resident set
    migrate_chunks: int = 1   # KV-migration ship split into this many
    # chunk hops (chunked_ppermute_compute's wave; 1 = one-shot)
    transport: str = "xla"    # migration ship transport, one of
    # TRANSPORTS — the same knob the p2p workloads carry
    # (xla = CollectivePermute, pallas_dma = raw async remote copies)
    # KV reuse (round 21, docs/kv_reuse.md) — both default-off,
    # preserving the baseline engine byte for byte:
    prefix_cache: bool = False  # content-hash full prompt pages into
    # a refcounted per-shard index; a matching prefix maps the shared
    # pages copy-on-write instead of re-prefilling them
    spec_k: int = 0           # speculative decoding: up to this many
    # draft tokens verified per decode step through ONE mixed step
    # (0 = off; the window additionally respects the chunk width and
    # the 8-row write band, so spec_k > chunk-1 never helps)

    def __post_init__(self) -> None:
        if self.page_len <= 0 or self.page_len % 8:
            raise ValueError(
                f"page_len must be a positive multiple of 8, got "
                f"{self.page_len}"
            )
        if self.chunk not in (1, 2, 4, 8):
            raise ValueError(
                f"chunk must be one of 1/2/4/8, got {self.chunk}"
            )
        if self.batching not in BATCHING:
            raise ValueError(
                f"unknown batching {self.batching!r}; expected one of "
                f"{BATCHING}"
            )
        for name in ("slots", "num_pages", "max_blocks", "requests",
                     "vocab"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be positive")
        if self.rate <= 0:
            raise ValueError(f"rate must be positive, got {self.rate}")
        if self.stop not in SERVE_STOPS:
            raise ValueError(
                f"unknown stop {self.stop!r}; expected one of "
                f"{SERVE_STOPS}"
            )
        if self.stop == "eos" and not 0.0 < self.eos_prob < 1.0:
            raise ValueError(
                f"stop='eos' needs eos_prob in (0, 1), got "
                f"{self.eos_prob}"
            )
        if self.queue_depth < 0 or self.deadline_steps < 0:
            raise ValueError(
                "queue_depth and deadline_steps must be >= 0 "
                "(0 disables)"
            )
        for name in ("prompt_len", "gen_len"):
            lo, hi = getattr(self, name)
            if lo < 1 or hi < lo:
                raise ValueError(
                    f"{name} must be an inclusive 1 <= LO <= HI "
                    f"range, got {(lo, hi)}"
                )
        window = self.max_blocks * self.page_len
        need = self.prompt_len[1] + self.gen_len[1]
        if need > window:
            raise ValueError(
                f"worst-case request ({need} tokens) overruns the "
                f"max_blocks*page_len window ({window})"
            )
        if self.transport not in TRANSPORTS:
            raise ValueError(
                f"unknown transport {self.transport!r}; expected one "
                f"of {TRANSPORTS}"
            )
        if self.migrate_chunks < 1:
            raise ValueError(
                f"migrate_chunks must be >= 1, got {self.migrate_chunks}"
            )
        if not 0 <= self.spec_k <= 7:
            raise ValueError(
                f"spec_k must be in 0..7 (a decode window of 1 + "
                f"spec_k tokens can never exceed the 8-row write "
                f"band), got {self.spec_k}"
            )
        if self.prefill_tp < 0 or self.prefill_pages < 0:
            raise ValueError(
                "prefill_tp and prefill_pages must be >= 0 (0 = auto)"
            )
        if self.prefill_slots <= 0:
            raise ValueError(
                f"prefill_slots must be positive, got "
                f"{self.prefill_slots}"
            )
