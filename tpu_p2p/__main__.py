"""``python -m tpu_p2p`` — the ``p2p_matrix`` binary's entry point
(reference launch contract: ``/root/reference/README.md:5``)."""

import sys

from tpu_p2p.cli import main

if __name__ == "__main__":
    sys.exit(main())
