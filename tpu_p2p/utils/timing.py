"""L6 — timing and metrics.

TPU-native equivalent of the reference's measurement core: host
wall-clock bracketing of a barrier-fenced transfer loop
(``/root/reference/p2p_matrix.cc:153,174-177`` uni;
``:208,255-258`` bi), with three deliberate upgrades flagged in
SURVEY.md §5/§6:

1. **Monotonic clock.** The reference uses
   ``std::chrono::system_clock`` (wall time — NTP steps skew results);
   we use a monotonic nanosecond clock (native C++ ``clock_gettime``
   via :mod:`tpu_p2p.utils.native` when built, else
   ``time.perf_counter_ns``).
2. **Per-iteration samples.** The reference keeps only the mean over
   128 iterations (``p2p_matrix.cc:176``); we retain every sample so
   p50/p99 exist (BASELINE.json's p50-latency metric requires them).
   The mean over the whole fenced region still reproduces the
   reference's number exactly.
3. **Warm-up.** XLA compiles on first call; warm-up iterations are
   mandatory before timing or the first cell absorbs compile time
   (SURVEY.md §5 "distributed communication backend" difference (b)).
   The reference needs none (NCCL setup happens at init).

Completion semantics: ``jax.block_until_ready`` is the analogue of
``cudaStreamSynchronize`` (``p2p_matrix.cc:162,170,229-230,250-251``).

Failure detection (additive — SURVEY.md §5): a watchdog thread turns a
wedged link into a :class:`~tpu_p2p.utils.errors.TransferTimeout`
instead of the reference's behavior of hanging the job at the next
``MPI_Barrier``.
"""

from __future__ import annotations

import math
import statistics
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import jax

from tpu_p2p.utils.errors import TransferTimeout

Clock = Callable[[], int]  # monotonic nanoseconds


def default_clock() -> Clock:
    """Native monotonic clock when the C++ lib is built, else Python's."""
    try:
        from tpu_p2p.utils import native

        if native.available():
            return native.monotonic_ns
    except Exception:  # pragma: no cover - defensive
        pass
    return time.perf_counter_ns


@dataclass
class Samples:
    """Per-iteration timings plus the fenced-region total.

    ``mean_region`` reproduces the reference's metric exactly:
    total elapsed between the two barriers divided by iteration count
    (``p2p_matrix.cc:174-176``). Percentiles come from the retained
    per-iteration samples (our addition).
    """

    iter_seconds: list = field(default_factory=list)
    region_seconds: float = 0.0
    timed_out: bool = False

    @property
    def count(self) -> int:
        return len(self.iter_seconds)

    @property
    def mean_region(self) -> float:
        # p2p_matrix.cc:176 — elapsed / count
        if self.timed_out or not self.count:
            return math.nan
        return self.region_seconds / self.count

    @property
    def mean(self) -> float:
        if self.timed_out or not self.count:
            return math.nan
        return sum(self.iter_seconds) / self.count

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over per-iteration samples."""
        if self.timed_out or not self.count:
            return math.nan
        try:
            from tpu_p2p.utils import native

            if native.available():
                return native.percentile(self.iter_seconds, q)
        except Exception:  # pragma: no cover
            pass
        s = sorted(self.iter_seconds)
        rank = max(0, min(len(s) - 1, math.ceil(q / 100.0 * len(s)) - 1))
        return s[rank]

    @property
    def p50(self) -> float:
        return self.percentile(50.0)

    @property
    def p99(self) -> float:
        return self.percentile(99.0)

    @property
    def min(self) -> float:
        return min(self.iter_seconds) if self.iter_seconds else math.nan


def gbps(nbytes: int, seconds: float, directions: int = 1) -> float:
    """Throughput in Gbps — the reference formula, bit-for-bit.

    ``msg_size * 8. / time / 1e9`` (``p2p_matrix.cc:177``), with
    ``directions=2`` applying the bi-directional ``* 2``
    (``p2p_matrix.cc:258``).
    """
    if seconds != seconds or seconds <= 0.0:  # NaN or degenerate
        return math.nan
    return nbytes * 8.0 / seconds / 1e9 * directions


def _block(value, timeout_s: Optional[float]) -> None:
    """``block_until_ready`` with an optional watchdog.

    With no timeout this is exactly the ``cudaStreamSynchronize``
    analogue. With one, a wedged transfer raises
    :class:`TransferTimeout` rather than hanging the sweep (the
    reference job would stall at ``MPI_Barrier`` until the launcher
    killed it — SURVEY.md §5 failure detection).
    """
    if timeout_s is None:
        jax.block_until_ready(value)
        return
    done = threading.Event()
    err: list = []

    def waiter():
        try:
            jax.block_until_ready(value)
        except Exception as e:  # pragma: no cover - device failure path
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise TransferTimeout(f"transfer exceeded {timeout_s}s watchdog")
    if err:
        raise err[0]


def measure_serialized(
    fn: Callable,
    x,
    iters: int,
    *,
    warmup: int = 1,
    clock: Optional[Clock] = None,
    timeout_s: Optional[float] = None,
    barrier: Optional[Callable[[], None]] = None,
) -> Samples:
    """Reference-semantics measurement: one message in flight, ever.

    Reproduces the uni-directional hot loop's structure
    (``p2p_matrix.cc:146-176``): barrier → start clock → ``iters`` ×
    {dispatch; drain} → barrier → stop clock. The per-message drain
    (``p2p_matrix.cc:162,170``) is ``block_until_ready`` on each call's
    result, which also charges dispatch overhead to the measurement,
    exactly as the reference charges launch overhead (SURVEY.md §3.3).
    """
    clock = clock or default_clock()
    s = Samples()
    try:
        for _ in range(max(0, warmup)):
            _block(fn(x), timeout_s)
    except TransferTimeout:
        # A pair that wedges on its very first (warm-up) transfer must
        # still become a marked cell, not a crashed sweep.
        s.timed_out = True
        return s
    if barrier is not None:
        barrier()  # p2p_matrix.cc:146
    t_region0 = clock()
    try:
        for _ in range(iters):
            t0 = clock()
            _block(fn(x), timeout_s)
            s.iter_seconds.append((clock() - t0) / 1e9)
    except TransferTimeout:
        s.timed_out = True
        return s
    if barrier is not None:
        barrier()  # p2p_matrix.cc:173
    s.region_seconds = (clock() - t_region0) / 1e9
    return s


def readback_fence(value) -> None:
    """Completion fence via a 1-element device→host readback.

    ``block_until_ready`` is the normal ``cudaStreamSynchronize``
    analogue, but on relayed/remote PJRT platforms (e.g. the axon TPU
    tunnel in this dev environment) it can return on *enqueue-ack*
    rather than completion — measured here as a v5e "achieving" 32
    PFLOP/s. Fetching one element of the result cannot complete before
    the computation has, on any platform.

    Multi-process arrays are not fully addressable; there, read back an
    element of this process's first local shard instead (fences local
    completion; cross-host alignment is the caller's barrier's job).
    """
    leaf = jax.tree_util.tree_leaves(value)[0]
    if getattr(leaf, "is_fully_addressable", True):
        jax.device_get(leaf.ravel()[0])
    else:
        shard = leaf.addressable_shards[0].data
        jax.device_get(shard.ravel()[0])


_fence_trust: Optional[bool] = None


def block_fence_is_trustworthy(refresh: bool = False) -> bool:
    """Does ``block_until_ready`` actually wait for completion here?

    Times a fixed compute chain under both fences; if the block fence
    claims to finish in under half the readback-fenced time, it is not
    waiting. Cached after first call.
    """
    global _fence_trust
    if _fence_trust is not None and not refresh:
        return _fence_trust
    import jax.numpy as jnp

    # One big single op (no chain): several ms of real device time. A
    # lying fence returns in tens of microseconds; an honest one takes
    # at least a large fraction of the readback-fenced time. The
    # readback includes host-transfer overhead, so on honest-but-slow
    # tunnels this check may conservatively report False — and the
    # differential fallback is correct there anyway.
    k = 4096
    a = jnp.ones((k, k), jnp.bfloat16)
    f = jax.jit(lambda x: x @ x)
    readback_fence(f(a))  # compile + warm
    t0 = time.perf_counter_ns()
    jax.block_until_ready(f(a))
    t_block = time.perf_counter_ns() - t0
    t0 = time.perf_counter_ns()
    readback_fence(f(a))
    t_read = time.perf_counter_ns() - t0
    _fence_trust = t_block >= 0.3 * t_read
    return _fence_trust


def run_fenced(value, timeout_s: Optional[float] = None,
               fence: Callable = readback_fence) -> None:
    """``fence(value)`` under the watchdog contract: with a timeout, a
    wedged transfer raises :class:`TransferTimeout` instead of hanging
    (shared by the host differential and the device-trace capture —
    every timed execution path honors ``--timeout`` identically)."""
    if timeout_s is None:
        fence(value)
        return
    done = threading.Event()
    err: list = []

    def waiter():
        try:
            fence(value)
        except Exception as e:  # pragma: no cover - device failure
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise TransferTimeout(f"transfer exceeded {timeout_s}s watchdog")
    if err:
        raise err[0]


def measure_differential(
    make_chain: Callable[[int], Callable],
    x,
    iters: int,
    *,
    repeats: int = 3,
    clock: Optional[Clock] = None,
    fence: Callable = readback_fence,
    timeout_s: Optional[float] = None,
    barrier: Optional[Callable[[], None]] = None,
) -> Samples:
    """Per-message time as the slope between two chain lengths.

    ``time(chain(iters)) - time(chain(short))`` divided by
    ``iters - short`` cancels *every* constant per-call cost — host
    dispatch, relay/tunnel round-trips, fence overhead — leaving pure
    device-side per-hop time. This is the only honest bandwidth
    measurement on platforms where the block fence is untrustworthy
    (see :func:`readback_fence`), and a useful dispatch-free metric
    everywhere (SURVEY.md §7 hard parts (b)/(e)).
    """
    clock = clock or default_clock()
    short = max(1, iters // 8)
    if short >= iters:
        iters = short + 1
    f_short, f_long = make_chain(short), make_chain(iters)

    def fenced(value):
        # Same watchdog contract as _block: a wedged link becomes a
        # marked cell, not a hung sweep.
        run_fenced(value, timeout_s, fence)

    s = Samples()
    try:
        fenced(f_short(x))  # compile + warm
        fenced(f_long(x))
        if barrier is not None:
            barrier()
        for _ in range(repeats):
            t0 = clock()
            fenced(f_short(x))
            t_short = (clock() - t0) / 1e9
            t0 = clock()
            fenced(f_long(x))
            t_long = (clock() - t0) / 1e9
            # Raw slope, unclamped: noise can make a sample negative
            # when per-op time is tiny vs constant overhead; the median
            # below absorbs that better than clamping would.
            s.iter_seconds.append((t_long - t_short) / (iters - short))
        if barrier is not None:
            barrier()
    except TransferTimeout:
        s.timed_out = True
        return s
    # Robust point estimate: the median over repeats, clamped at zero
    # (gbps() maps a zero/NaN per-op time to NaN rather than inf).
    med = statistics.median(s.iter_seconds) if s.iter_seconds else math.nan
    s.region_seconds = max(0.0, med) * len(s.iter_seconds)
    return s


def measure_fused(
    chain_fn: Callable,
    x,
    iters: int,
    *,
    repeats: int = 3,
    warmup: int = 1,
    clock: Optional[Clock] = None,
    timeout_s: Optional[float] = None,
    barrier: Optional[Callable[[], None]] = None,
) -> Samples:
    """Device-serialized measurement without host dispatch overhead.

    ``chain_fn`` runs ``iters`` data-dependent hops inside one XLA
    program (:meth:`CollectiveCache.permute_chain`); each timed sample
    is one whole chain divided by ``iters``. This is the pipelined-peak
    counterpart the reference cannot express (its per-iteration stream
    sync forbids it — SURVEY.md §3.3 "key semantic"), labeled
    separately so the two are never conflated (§7 hard part (c)).
    """
    clock = clock or default_clock()
    s = Samples()
    try:
        for _ in range(max(0, warmup)):
            _block(chain_fn(x), timeout_s)
    except TransferTimeout:
        s.timed_out = True
        return s
    if barrier is not None:
        barrier()
    t_region0 = clock()
    try:
        for _ in range(repeats):
            t0 = clock()
            _block(chain_fn(x), timeout_s)
            per_iter = (clock() - t0) / 1e9 / iters
            s.iter_seconds.append(per_iter)
    except TransferTimeout:
        s.timed_out = True
        return s
    if barrier is not None:
        barrier()
    # mean_region divides by len(iter_seconds) == repeats; pre-dividing the
    # fenced elapsed by `iters` makes mean_region = elapsed/(repeats*iters),
    # i.e. seconds per message, matching measure_serialized's units.
    s.region_seconds = (clock() - t_region0) / 1e9 / iters
    return s
