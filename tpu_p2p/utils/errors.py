"""L8 — fail-fast error handling (cross-cutting).

TPU-native equivalent of the reference's three check macros
``MPICHECK`` / ``CUDACHECK`` / ``NCCLCHECK``
(``/root/reference/p2p_matrix.cc:15-42``), which print
``Failed: <backend> error <file>:<line> '<err>'`` and
``exit(EXIT_FAILURE)``, and of the topology-violation
``exit(-1)`` paths (``p2p_matrix.cc:85,97``).

In a Python framework the idiomatic shape is typed exceptions raised at
the failure site (carrying the caller's file:line, like ``__FILE__`` /
``__LINE__`` in the macros) plus a single CLI-level handler
(:func:`fail_fast`) that formats and exits — same fail-fast observable
behavior, one handler instead of 34 macro call sites.
"""

from __future__ import annotations

import sys
import traceback
from contextlib import contextmanager


class TpuP2PError(RuntimeError):
    """Base class for all framework errors."""


class PlacementError(TpuP2PError):
    """Topology/placement invariant violated.

    Parity: the ``exit(-1)`` paths of ``check_process_placement_policy``
    (``p2p_matrix.cc:83-86`` non-uniform processes per host;
    ``p2p_matrix.cc:88-98`` non-contiguous per-host rank blocks).
    """


class BackendError(TpuP2PError):
    """A JAX/XLA-level operation failed.

    Parity: ``NCCLCHECK``/``CUDACHECK`` (``p2p_matrix.cc:25-42``) — any
    device/collective call failing is fatal to the benchmark.
    """


class TransferTimeout(TpuP2PError):
    """A timed transfer exceeded its watchdog deadline.

    Strictly additive vs. the reference, which hangs at the next
    ``MPI_Barrier`` if a link wedges (SURVEY.md §5 failure detection):
    we detect the wedge and surface it as a marked cell instead.
    """


def _caller_site(depth: int = 2) -> str:
    """``file:line`` of the calling frame — the ``__FILE__:__LINE__`` of
    the macros at ``p2p_matrix.cc:18,28,38``."""
    frame = sys._getframe(depth)
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


def check(cond: bool, msg: str, exc: type = TpuP2PError) -> None:
    """Assert a runtime invariant, failing with the call site attached.

    Equivalent of the reference's single bare ``assert``
    (``p2p_matrix.cc:106``) and the macro checks, as a function.
    """
    if not cond:
        raise exc(f"Failed: {msg} at {_caller_site()}")


@contextmanager
def checked(what: str):
    """Wrap a backend call so failures carry context + call site.

    Usage parity with ``NCCLCHECK(ncclSend(...))``::

        with checked("ppermute dispatch"):
            out = fn(x)
    """
    site = _caller_site(3)  # capture at entry: 0=_caller_site, 1=checked,
    # 2=contextmanager.__enter__, 3=the user's `with` statement
    try:
        yield
    except TpuP2PError:
        raise
    except Exception as e:  # noqa: BLE001 — deliberate catch-all, macro parity
        raise BackendError(
            f"Failed: {what} error {site} '{type(e).__name__}: {e}'"
        ) from e


def fail_fast(e: BaseException, *, stream=None) -> "int":
    """CLI-level handler: print like the reference macros, return exit code.

    Topology errors go to stderr with exit code 255 (two's-complement of
    the reference's ``exit(-1)``, ``p2p_matrix.cc:85,97``); everything
    else prints the macro-style ``Failed: ...`` line and returns 1
    (``EXIT_FAILURE``, ``p2p_matrix.cc:20,30,40``).
    """
    stream = stream if stream is not None else sys.stderr
    if isinstance(e, PlacementError):
        print(str(e), file=stream)
        return 255
    print(f"Failed: {type(e).__name__} '{e}'", file=stream)
    if not isinstance(e, TpuP2PError):
        traceback.print_exception(e, file=stream)
    return 1
