"""Input pipeline — sharded host→device loading with prefetch.

The reference has no input path at all (its buffers are allocated once
and zeroed on device, ``/root/reference/p2p_matrix.cc:124-130``); a
training framework needs one, and on TPU its shape is dictated by two
facts: ``device_put`` is asynchronous (the transfer is enqueued, not
awaited), and a step's host→device copies can hide entirely under the
previous step's compute if they are issued early enough. So the loader
is just disciplined use of the runtime:

- :class:`DeviceLoader` wraps any iterator of host batches (numpy
  arrays or pytrees of them) and keeps ``prefetch`` batches in flight
  on device: each ``next()`` returns an already-transferring batch and
  tops the queue back up, so the copy for step ``i+k`` overlaps the
  compute of step ``i``. No threads — async dispatch is the engine.
- Sharding is first-class: every batch lands distributed per a
  ``PartitionSpec`` over the mesh. Under multi-host each process feeds
  only its *local* shard and the loader assembles the global
  ``jax.Array`` (``make_array_from_process_local_data``), so no host
  ever materializes the global batch.
- :func:`synthetic_batches` supplies the benchmark/test source: seeded
  random batches shaped for the flagship model.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

Batch = Any  # a numpy array or an arbitrary pytree of them


class DeviceLoader:
    """Iterate device-resident, mesh-sharded batches with prefetch.

    ``source`` yields host batches (pytrees of numpy arrays) whose
    leading dims match ``spec`` — the *global* batch on single-host,
    this process's row-block of it under multi-host.
    """

    def __init__(self, source: Iterable[Batch], mesh: Mesh,
                 spec: PartitionSpec, prefetch: int = 2) -> None:
        if prefetch < 1:
            raise ValueError(f"prefetch must be >= 1, got {prefetch}")
        self._it = iter(source)
        self._mesh = mesh
        self._sharding = NamedSharding(mesh, spec)
        self._prefetch = prefetch
        self._queue: deque = deque()
        self._exhausted = False
        self._error: Optional[Exception] = None

    def _put(self, host_batch: Batch):
        if jax.process_count() > 1:
            return jax.tree.map(
                lambda a: jax.make_array_from_process_local_data(
                    self._sharding, np.asarray(a)
                ),
                host_batch,
            )
        return jax.device_put(host_batch, self._sharding)

    def _fill(self) -> None:
        while (not self._exhausted and self._error is None
               and len(self._queue) < self._prefetch):
            try:
                self._queue.append(self._put(next(self._it)))
            except StopIteration:
                self._exhausted = True
            except Exception as e:  # noqa: BLE001 — deferred below.
                # Don't let a source error during top-up swallow batches
                # already in flight: park it and surface it only once
                # the queue has drained. Exception, not BaseException:
                # KeyboardInterrupt/SystemExit must propagate now.
                self._error = e

    def __iter__(self) -> Iterator[Batch]:
        return self

    def __next__(self) -> Batch:
        self._fill()
        if self._queue:
            batch = self._queue.popleft()
            self._fill()  # keep the pipe full before handing control back
            return batch
        if self._error is not None:
            e, self._error = self._error, None
            raise e
        raise StopIteration

    @property
    def in_flight(self) -> int:
        """Batches currently enqueued on device (tests/introspection)."""
        return len(self._queue)


def synthetic_batches(shape, *, count: Optional[int] = None, seed: int = 0,
                      dtype=np.float32,
                      make: Optional[Callable[[np.random.Generator], Batch]] = None
                      ) -> Iterator[Batch]:
    """Seeded random host batches — the framework's benchmark source.

    Yields ``count`` batches (infinite when None) of ``shape``; pass
    ``make`` to build arbitrary pytree batches from the generator
    (e.g. ``lambda r: {"x": ..., "y": ...}``).
    """
    rng = np.random.default_rng(seed)
    i = 0
    while count is None or i < count:
        if make is not None:
            yield make(rng)
        else:
            yield rng.standard_normal(shape).astype(dtype)
        i += 1


def flagship_loader(cfg, mesh: Mesh, *, count: Optional[int] = None,
                    seed: int = 0, prefetch: int = 2) -> DeviceLoader:
    """A ready-to-train loader of ``(x, target)`` flagship batches,
    sharded like :func:`tpu_p2p.models.flagship.flagship_data_spec`."""
    from tpu_p2p.models.flagship import flagship_data_spec, flagship_host_batch

    return DeviceLoader(
        synthetic_batches(None, count=count, seed=seed,
                          make=lambda rng: flagship_host_batch(cfg, rng)),
        mesh, flagship_data_spec(mesh), prefetch=prefetch,
    )
