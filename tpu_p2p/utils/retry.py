"""Bounded retry with exponential backoff for transient IO failures.

The durable checkpoint path (:mod:`tpu_p2p.utils.checkpoint`) writes
every generation file through this helper: real storage — NFS mounts,
object-store FUSE layers, a busy local disk — fails *transiently* far
more often than it fails permanently (MegaScale, Jiang et al. 2024
reports storage-side blips dominating large-run downtime), and a save
that dies on the first EIO turns a recoverable hiccup into a lost
generation. The policy here is deliberately minimal and deterministic:
a fixed attempt budget, exponential backoff with no jitter (the test
suite and the ``make ckpt-chaos`` smoke must be able to predict the
exact attempt count for an injected first-N-failures fault), and a
narrow default exception filter — ``OSError`` only. A
:class:`~tpu_p2p.obs.faults.SimulatedCrash` derives from
``BaseException`` precisely so no retry filter can swallow a simulated
process death.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["retry_io", "DEFAULT_ATTEMPTS"]

# Attempt budget shared by every checkpoint write: the injected
# transient-IO fault (FaultPlan.ckpt_io_errors) must fail fewer
# attempts than this for the ckpt-chaos transient_io scenario to
# succeed with zero fallbacks — the smoke grades exactly that margin.
DEFAULT_ATTEMPTS = 5


def retry_io(fn: Callable, *, attempts: int = DEFAULT_ATTEMPTS,
             base_delay_s: float = 0.002, backoff: float = 2.0,
             retry_on: Tuple[Type[BaseException], ...] = (OSError,),
             sleep: Callable[[float], None] = time.sleep,
             on_retry: Optional[Callable[[int, BaseException], None]]
             = None):
    """Call ``fn()`` up to ``attempts`` times, sleeping
    ``base_delay_s * backoff**k`` after the k-th failure.

    Only exceptions matching ``retry_on`` are retried; anything else
    (including a ``BaseException`` like
    :class:`~tpu_p2p.obs.faults.SimulatedCrash`) propagates
    immediately — a simulated process death must never look like a
    retryable blip. The final failure re-raises the last exception
    unchanged. ``on_retry(attempt_index, exc)`` is called before each
    backoff sleep (1-based index of the attempt that just failed) so
    callers can count retries into their telemetry.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delay = float(base_delay_s)
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                sleep(delay)
            delay *= backoff
