"""Device-event timing validation from ``jax.profiler`` traces.

Closes the loop the round-1 verdict called out (missing #3): the north
star says "``cudaEvent_t`` timing becomes XLA device-event timing", and
SURVEY.md §5/§7(b) calls for cross-checking host timing against
``jax.profiler`` device traces — round 1 captured traces
(``--profile-dir``) but nothing ever consumed them.

What this module does: parse the Chrome-trace JSON that
``jax.profiler.trace`` writes (``plugins/profile/*//*.trace.json.gz``),
pull out the *device-track* events (process names ``/device:TPU:N`` —
these are XLA's own per-op/per-program device timeline, the TPU
analogue of ``cudaEvent_t`` intervals), and compare a
device-side differential slope against the host-side
:func:`tpu_p2p.utils.timing.measure_differential` slope for the same
two chain programs. Agreement means the host differential number is
real device time, not an artifact of the fence heuristic
(``timing.block_fence_is_trustworthy`` no longer carries the trust
story alone).

Zero new dependencies: the ``.trace.json.gz`` is gzip + JSON. The
``.xplane.pb`` twin needs TF profiler protos, which this image does not
ship — and the JSON carries the same device track.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from dataclasses import dataclass
from typing import Callable, List, Optional

__all__ = [
    "DeviceEvent",
    "TimingValidation",
    "HeadlineMeasurement",
    "latest_trace_file",
    "load_trace_events",
    "device_top_level_events",
    "device_leaf_events",
    "device_collective_intervals",
    "device_busy_fraction",
    "differential_from_trace",
    "gather_overlap_fraction",
    "tp_overlap_fraction",
    "ep_overlap_fraction",
    "pp_overlap_fraction",
    "validate_differential",
    "measure_headline",
]


@dataclass(frozen=True)
class DeviceEvent:
    """One complete ('X') event on a device track, seconds units."""

    name: str
    ts: float  # seconds since trace epoch
    dur: float  # seconds
    pid: int
    tid: int


def latest_trace_file(trace_dir: str) -> str:
    """Newest ``*.trace.json.gz`` under a ``jax.profiler.trace`` dir."""
    hits = sorted(
        glob.glob(
            os.path.join(trace_dir, "plugins", "profile", "*",
                         "*.trace.json.gz")
        )
    )
    if not hits:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {trace_dir!r} — was the run "
            "wrapped in jax.profiler.trace()?"
        )
    return hits[-1]


def load_trace_events(trace_dir: str):
    """→ (X-events, {pid: process_name}) from the newest trace."""
    with gzip.open(latest_trace_file(trace_dir), "rt") as fh:
        trace = json.load(fh)
    events = trace.get("traceEvents", [])
    pid_names = {
        e["pid"]: e.get("args", {}).get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    xs = [e for e in events if e.get("ph") == "X" and "dur" in e]
    return xs, pid_names


def device_top_level_events(trace_dir: str) -> List[DeviceEvent]:
    """Outermost events on device tracks, in launch order.

    A device track nests op events (``fusion``, ``copy-start``…) inside
    whole-program events (``jit_foo(…)``); the outermost interval is
    the device-resident wall time of one executable run — including
    device-side gaps between its ops, which is exactly what a
    chain-program measurement means by "per-program time". Containment
    is computed per (pid, tid) by interval nesting.
    """
    xs, pid_names = load_trace_events(trace_dir)
    dev_pids = {p for p, n in pid_names.items()
                if str(n).startswith("/device:")}
    by_track: dict = {}
    for e in xs:
        if e["pid"] in dev_pids:
            by_track.setdefault((e["pid"], e["tid"]), []).append(e)
    out: List[DeviceEvent] = []
    for (pid, tid), evs in by_track.items():
        # Sort by start asc, then duration desc: a containing interval
        # always precedes its contents, so one stack pass finds tops.
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        top_end = -1.0
        for e in evs:
            if e["ts"] >= top_end:  # not inside the current top event
                out.append(DeviceEvent(
                    name=e.get("name", ""), ts=e["ts"] / 1e6,
                    dur=e["dur"] / 1e6, pid=pid, tid=tid,
                ))
                top_end = e["ts"] + e["dur"]
    out.sort(key=lambda d: d.ts)
    return out


def device_op_events(trace_dir: str) -> List[DeviceEvent]:
    """Op-level events on device tracks: device X-events nested at
    depth exactly 1 inside a containing (program) event. These are
    XLA's per-op rows (``fusion.N``, ``copy.N``,
    ``dynamic-update-slice.N``, Pallas ``custom-call``s, collective
    ops) — the raw material for attributing a step's device time by op
    category. Depth-1 only: deeper nesting (an op's sub-events) would
    double-count the parent's duration in any aggregation."""
    xs, pid_names = load_trace_events(trace_dir)
    dev_pids = {p for p, n in pid_names.items()
                if str(n).startswith("/device:")}
    by_track: dict = {}
    for e in xs:
        if e["pid"] in dev_pids:
            by_track.setdefault((e["pid"], e["tid"]), []).append(e)
    out: List[DeviceEvent] = []
    for (pid, tid), evs in by_track.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        ends: list = []  # stack of enclosing-interval end times
        for e in evs:
            while ends and e["ts"] >= ends[-1]:
                ends.pop()
            if len(ends) == 1:  # direct child of a top-level event
                out.append(DeviceEvent(
                    name=e.get("name", ""), ts=e["ts"] / 1e6,
                    dur=e["dur"] / 1e6, pid=pid, tid=tid,
                ))
            ends.append(e["ts"] + e["dur"])
    out.sort(key=lambda d: d.ts)
    return out


# Op-name → category rules for roofline attribution, checked in order
# (first match wins). Rules are prefix/substring heuristics over XLA's
# HLO op names as they appear on the device track; "fusion" is the
# catch-all XLA bucket for fused elementwise+matmul regions, so it is
# matched LAST among compute ops and callers should read it as "fused
# compute (matmul and/or elementwise)".
OP_CATEGORY_RULES = (
    # ``dma_transport`` = the round-11 Pallas raw-DMA permute kernels
    # (tpu_p2p/parallel/pallas_dma.py — every kernel there carries the
    # prefix precisely so its device events classify as TRANSPORT, not
    # "kernel"): they move bytes across the mesh, so the obs join and
    # the overlap fractions must see them next to collective-permute.
    ("collective", ("all-reduce", "all-gather", "all-to-all",
                    "collective-permute", "reduce-scatter",
                    "dma_transport", "collective")),
    # This framework's Pallas kernels appear on the device track under
    # their jitted Python names (e.g. ``_flash_bwd_call.188``), not as
    # ``custom-call`` — checked BEFORE the copy rules so
    # ``cache_row_write`` (tpu_p2p/ops/kvcache.py) is a kernel, not a
    # "write" false-positive.
    ("kernel", ("custom-call", "_flash_call", "_flash_bwd_call",
                "_dq_reduce", "cache_row_write")),
    ("copy", ("copy", "bitcast", "transpose", "slice", "concatenate",
              "dynamic-update-slice", "dynamic-slice", "pad", "gather",
              "scatter", "reshape", "broadcast")),
    ("matmul", ("dot", "convolution", "cublas", "gemm")),
    ("fusion", ("fusion", "loop_", "input_", "output_")),
)


def categorize_op(name: str) -> str:
    """Map one device op-event name to a roofline category."""
    base = name.lower()
    for cat, subs in OP_CATEGORY_RULES:
        for s in subs:
            if s in base:
                return cat
    return "other"


def _leaf_and_dropped_events(trace_dir: str, loaded=None):
    """→ ``(leaves, dropped)``: innermost nested device events, plus
    the childless depth-0 events the leaf view excludes by design.

    The exclusion rule (see :func:`device_leaf_events`) assumes real
    op rows are always nested inside their program's jit_* span; the
    ``dropped`` list is returned so callers can *account* for the time
    that assumption throws away instead of losing it silently — a
    trace that violates it (ops recorded unnested) would otherwise
    read as a shorter program than the device ran.

    ``loaded``: optional pre-parsed ``(xs, pid_names)`` from
    :func:`load_trace_events`, so a caller that already paid the
    gzip+JSON parse (traces are routinely tens of MB) does not pay it
    twice.
    """
    xs, pid_names = (load_trace_events(trace_dir) if loaded is None
                     else loaded)
    dev_pids = {p for p, n in pid_names.items()
                if str(n).startswith("/device:")}
    by_track: dict = {}
    for e in xs:
        if e["pid"] in dev_pids:
            by_track.setdefault((e["pid"], e["tid"]), []).append(e)
    out: List[DeviceEvent] = []
    dropped: List[DeviceEvent] = []
    for (pid, tid), evs in by_track.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list = []  # [(end_ts, event, had_child, depth)]

        def flush_until(ts):
            while stack and ts >= stack[-1][0]:
                end, ev, had_child, depth = stack.pop()
                # A leaf must be NESTED (depth >= 1): real op rows
                # always sit inside their program's jit_* span on the
                # op tid. Childless depth-0 rows are never ops — the
                # program-mirror tid's jit_* span (counting it doubled
                # the total: measured 200% coverage on the r5 LM-step
                # trace), the second thread's top-level op-row copies,
                # and async copy-start/copy-done transfer rows — all
                # of which depth-1 attribution also excludes.
                if not had_child:
                    (out if depth > 0 else dropped).append(DeviceEvent(
                        name=ev.get("name", ""), ts=ev["ts"] / 1e6,
                        dur=ev["dur"] / 1e6, pid=pid, tid=tid,
                    ))

        for e in evs:
            flush_until(e["ts"])
            if stack:
                stack[-1] = (stack[-1][0], stack[-1][1], True,
                             stack[-1][3])
            stack.append((e["ts"] + e["dur"], e, False, len(stack)))
        flush_until(float("inf"))
    out.sort(key=lambda d: d.ts)
    dropped.sort(key=lambda d: d.ts)
    return out, dropped


def device_leaf_events(trace_dir: str) -> List[DeviceEvent]:
    """Innermost (childless) events on device tracks.

    Depth-1 attribution (:func:`device_op_events`) is blind inside
    control flow: a step structured as ``lax.scan`` loops shows up as
    one opaque ``while`` op covering 80-90% of the program (measured
    on the round-5 production-shape LM step). Leaf events descend to
    the ops the device actually ran — and, like depth-1, they cannot
    double-count: no leaf contains another event. Childless depth-0
    events are dropped (never ops on traces following XLA's nesting
    convention); :func:`op_category_breakdown` reports their total so
    a trace violating that convention is visible, not silently
    under-attributed.
    """
    return _leaf_and_dropped_events(trace_dir)[0]


def op_category_breakdown(trace_dir: str, window=None,
                          leaves: bool = False):
    """Aggregate device op time by category → ``{category:
    {"seconds": total, "count": n, "top": [(name, seconds), ...]}}``.

    ``window``: optional ``(t0, t1)`` seconds clipping to one program
    execution (e.g. a single step picked from
    :func:`device_top_level_events`) so warm-up and neighboring
    programs do not pollute the attribution. Events are counted on the
    lowest device pid only (multi-device traces repeat every program
    per track; see :func:`differential_from_trace`).

    ``leaves=True`` attributes innermost events instead of depth-1
    ops — required when the program wraps its work in ``lax.scan`` /
    ``while`` (pipeline ticks, chained steps), whose depth-1 view is
    one opaque ``while`` op. In this mode the result also carries a
    reserved ``"dropped_unnested"`` entry (same seconds/count/top
    shape, NOT an op category) whenever childless depth-0 events were
    excluded from the attribution — on a conforming trace that is the
    program-mirror span + async transfer rows, but on a trace
    violating the "ops are always nested" assumption it is real op
    time, and hiding it would make the program read faster than the
    device ran it.
    """
    dropped: List[DeviceEvent] = []
    if leaves:
        evs, dropped = _leaf_and_dropped_events(trace_dir)
    else:
        evs = device_op_events(trace_dir)
    if not evs and not dropped:
        return {}
    # pid0 from the leaves when any exist; a trace whose EVERY op row
    # is unnested (the convention violation dropped_unnested exists to
    # surface) must still report — falling back to the dropped rows'
    # pid rather than returning {} and vanishing all device time.
    pid0 = min(e.pid for e in (evs or dropped))

    def clip(rows):
        rows = [e for e in rows if e.pid == pid0]
        if window is not None:
            t0, t1 = window
            rows = [e for e in rows if t0 <= e.ts and e.ts + e.dur <= t1]
        return rows

    evs = clip(evs)
    out: dict = {}
    per_name: dict = {}
    for e in evs:
        cat = categorize_op(e.name)
        d = out.setdefault(cat, {"seconds": 0.0, "count": 0})
        d["seconds"] += e.dur
        d["count"] += 1
        key = (cat, e.name)
        per_name[key] = per_name.get(key, 0.0) + e.dur
    for cat, d in out.items():
        tops = sorted(
            ((n, s) for (c, n), s in per_name.items() if c == cat),
            key=lambda kv: -kv[1],
        )[:5]
        d["top"] = [(n, round(s, 9)) for n, s in tops]
        d["seconds"] = round(d["seconds"], 9)
    dropped = clip(dropped)
    if dropped:
        by_name: dict = {}
        for e in dropped:
            by_name[e.name] = by_name.get(e.name, 0.0) + e.dur
        tops = sorted(by_name.items(), key=lambda kv: -kv[1])[:5]
        out["dropped_unnested"] = {
            "seconds": round(sum(e.dur for e in dropped), 9),
            "count": len(dropped),
            "top": [(n, round(s, 9)) for n, s in tops],
        }
    return out


def _interval_union(intervals):
    """Merge ``[(t0, t1), ...]`` into a sorted disjoint union."""
    out = []
    for t0, t1 in sorted(intervals):
        if out and t0 <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t1))
        else:
            out.append((t0, t1))
    return out


def _union_len(u) -> float:
    return sum(t1 - t0 for t0, t1 in u)


def _intersect_len(a, b) -> float:
    """Total length of the intersection of two disjoint sorted unions."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _bridged_intervals(dev_evs, pid0: int, is_match):
    """Matching device events as bridged ``(name, t0, t1)`` intervals.

    The ONE implementation of the async-pair rule (shared by
    :func:`gather_overlap_fraction` and
    :func:`device_collective_intervals`, so the overlap fractions and
    the obs ledger's join can never disagree about what an interval
    is): XLA's ``*-start.N`` / ``*-done.N`` pairs bridge into one
    interval spanning start-begin → done-end — the in-flight gap
    between them IS the transfer — paired by the done-name after
    ts-sorting (Chrome-trace event order is not guaranteed, so the
    sort makes a pair's start always precede its done). Unpaired
    starts keep their own span; only ``pid0``'s events count.
    """
    starts: dict = {}
    out = []
    for e in sorted(dev_evs, key=lambda e: e["ts"]):
        name = e.get("name", "")
        if e["pid"] != pid0 or not is_match(name):
            continue
        t0, t1 = e["ts"] / 1e6, (e["ts"] + e["dur"]) / 1e6
        if "start" in name:
            starts[name.replace("start", "done")] = (name, t0, t1)
        elif name in starts:
            sname, s0, _ = starts.pop(name)
            out.append((sname, s0, t1))
        else:
            out.append((name, t0, t1))
    out.extend(starts.values())  # unpaired starts: own span only
    return out


def device_collective_intervals(trace_dir: str, window=None,
                                loaded=None):
    """Collective device events as bridged intervals →
    ``[(name, t0, t1), ...]`` sorted by start, seconds units — the
    trace-side input of the obs ledger's join
    (:func:`tpu_p2p.obs.ledger.join_trace`).

    Same event selection rules as :func:`gather_overlap_fraction`
    (shared :func:`_bridged_intervals`): lowest device pid only
    (multi-device traces repeat every program per track), async pairs
    bridged. An event counts as collective when :func:`categorize_op`
    says so. ``window``: optional ``(t0, t1)`` seconds filter
    (whole-interval containment). Returns ``None`` when the platform
    records no device track (the simulated CPU mesh) — distinct from
    a device trace that simply holds no collectives (empty list).
    """
    xs, pid_names = (load_trace_events(trace_dir) if loaded is None
                     else loaded)
    dev_pids = {p for p, n in pid_names.items()
                if str(n).startswith("/device:")}
    dev_evs = [e for e in xs if e.get("pid") in dev_pids]
    if not dev_evs:
        return None
    pid0 = min(e["pid"] for e in dev_evs)
    out = _bridged_intervals(
        dev_evs, pid0, lambda name: categorize_op(name) == "collective"
    )
    if window is not None:
        w0, w1 = window
        out = [(n, t0, t1) for n, t0, t1 in out if w0 <= t0 and t1 <= w1]
    out.sort(key=lambda r: r[1])
    return out


def device_busy_fraction(trace_dir: str, window=None):
    """Fraction of the device-trace span the device spent executing
    ops — the step timeline's device-side utilization column.

    Busy time is the disjoint union of the leaf events
    (:func:`device_leaf_events` — no leaf contains another, so the
    union cannot double-count) on the lowest device pid; the span is
    ``window`` when given, else first-leaf-start → last-leaf-end.
    → ``{"busy_s", "span_s", "frac"}`` or ``None`` when the platform
    records no device track.
    """
    leaves, _ = _leaf_and_dropped_events(trace_dir)
    if not leaves:
        return None
    pid0 = min(e.pid for e in leaves)
    rows = [(e.ts, e.ts + e.dur) for e in leaves if e.pid == pid0]
    if window is not None:
        t0, t1 = window
        rows = [r for r in rows if t0 <= r[0] and r[1] <= t1]
        span = t1 - t0
    else:
        span = (max(r[1] for r in rows) - min(r[0] for r in rows)
                if rows else 0.0)
    busy = _union_len(_interval_union(rows))
    return {
        "busy_s": busy,
        "span_s": span,
        "frac": (busy / span) if span > 0 else None,
    }


def gather_overlap_fraction(trace_dir: str,
                            names: tuple = ("all-gather",),
                            window=None) -> Optional[dict]:
    """Fraction of device all-gather time hidden under concurrent
    compute, from one ``jax.profiler.trace`` capture — the FSDP
    prefetch metric (``bench.py``'s ``fsdp_overlap_frac``), measured
    off the device timeline the same way ``flagship_large_mfu``'s
    step time is.

    Collective intervals: every device-track event whose name contains
    one of ``names``; XLA's async pairs (``all-gather-start.N`` /
    ``all-gather-done.N``) are bridged into one interval spanning
    start-begin → done-end, because the in-flight gap between them IS
    the transfer this metric asks about. Compute intervals: the leaf
    events (:func:`device_leaf_events`) of every non-collective
    category. Both sides are clipped to the lowest device pid (the
    multi-track convention of :func:`differential_from_trace`) and the
    optional ``(t0, t1)`` ``window``, merged into disjoint unions, and

        frac = |gather ∩ compute| / |gather|

    → ``{"frac", "gather_s", "hidden_s", "compute_s"}``; ``frac`` is
    ``None`` when the trace holds no matching collective (nothing to
    hide — a dp=1 mesh, or FSDP off). Returns ``None`` entirely when
    the platform records no device track (the simulated CPU mesh).
    """
    xs, pid_names = load_trace_events(trace_dir)
    dev_pids = {p for p, n in pid_names.items()
                if str(n).startswith("/device:")}
    dev_evs = [e for e in xs if e.get("pid") in dev_pids]
    if not dev_evs:
        return None
    pid0 = min(e["pid"] for e in dev_evs)

    def in_window(t0, t1):
        return window is None or (window[0] <= t0 and t1 <= window[1])

    def is_gather(name: str) -> bool:
        low = name.lower()
        return any(s in low for s in names)

    # Async-pair bridging shared with device_collective_intervals —
    # see _bridged_intervals for the pairing rules.
    gathers = [
        (t0, t1)
        for _n, t0, t1 in _bridged_intervals(dev_evs, pid0, is_gather)
        if in_window(t0, t1)
    ]
    leaves, _ = _leaf_and_dropped_events(trace_dir,
                                         loaded=(xs, pid_names))
    compute = [
        (e.ts, e.ts + e.dur) for e in leaves
        if e.pid == pid0 and categorize_op(e.name) != "collective"
        and in_window(e.ts, e.ts + e.dur)
    ]
    gu, cu = _interval_union(gathers), _interval_union(compute)
    gather_s = _union_len(gu)
    hidden_s = _intersect_len(gu, cu)
    return {
        "frac": (hidden_s / gather_s) if gather_s > 0 else None,
        "gather_s": gather_s,
        "hidden_s": hidden_s,
        "compute_s": _union_len(cu),
    }


def tp_overlap_fraction(trace_dir: str, window=None) -> Optional[dict]:
    """Fraction of device collective-permute time hidden under
    concurrent compute — the ``tp_overlap="ring"`` metric
    (``bench.py``'s ``tp_overlap_frac``), the tp twin of
    :func:`gather_overlap_fraction`.

    The ring Megatron joins (``flagship_forward._tp_ring_join``) move
    every byte over shift-by-1 ``ppermute`` hops, which XLA lowers to
    ``collective-permute(-start/-done)`` device events; the same
    interval algebra as the FSDP gather metric then measures how much
    of that transfer time rides under matmuls. Same return contract:
    ``None`` without a device track, ``frac=None`` when no
    collective-permute exists in the capture (tp=1 or ring off —
    nothing to hide). Note the flagship ring block also issues one
    ``psum`` per join combine; that op is *deliberately* excluded —
    the ring's claim is that the chunk transfers overlap, and the
    psum combine is the non-overlapped remainder the fraction should
    not flatter.
    """
    return gather_overlap_fraction(trace_dir,
                                   names=("collective-permute",),
                                   window=window)


def ep_overlap_fraction(trace_dir: str, window=None) -> Optional[dict]:
    """Fraction of device EP-transport time hidden under concurrent
    compute — the ``ep_overlap="ring"`` metric (``bench.py``'s
    ``ep_overlap_frac``), the a2a twin of
    :func:`gather_overlap_fraction` / :func:`tp_overlap_fraction`.

    Under ``ep_overlap="none"`` the MoE dispatch/combine reshards are
    ``all-to-all`` device events; under ``"ring"`` the same bytes move
    as shift-by-s ``collective-permute`` hops
    (``tpu_p2p/parallel/collectives.py ring_all_to_all_matmul`` /
    ``matmul_ring_all_to_all``) — this metric counts BOTH event
    families, so it reads the EP transport's hidden share in either
    mode from one capture (on the bench's pure-ep mesh no other
    permute ring runs, so every counted interval is EP transport; on
    mixed tp×ep meshes use ``tp_overlap_fraction``'s name filter to
    separate the families). Same return contract as the twins:
    ``None`` without a device track, ``frac=None`` when no matching
    collective exists in the capture (ep=1 — nothing to hide).
    """
    return gather_overlap_fraction(
        trace_dir, names=("all-to-all", "collective-permute"),
        window=window)


def pp_overlap_fraction(trace_dir: str, window=None) -> Optional[dict]:
    """Fraction of device collective-permute time hidden under
    concurrent compute — the ``pp_overlap="wave"`` metric
    (``bench.py``'s ``pp_overlap_frac``), the pipeline twin of
    :func:`gather_overlap_fraction` / :func:`tp_overlap_fraction`.

    The pipeline stage hop is a neighbor-edge ``ppermute`` in BOTH
    modes — one monolithic transfer per tick under ``"none"``, a
    token-chunk wave per tick under ``"wave"``
    (``tpu_p2p/parallel/collectives.py chunked_ppermute_compute``) —
    and XLA lowers either to ``collective-permute(-start/-done)``
    device events, so one capture reads the stage transport's hidden
    share in either mode (on the bench's pure-pp mesh no other permute
    family runs; mixed tp×pp / sp×pp meshes share the event name and
    need a pure mesh to attribute). Same return contract as the twins:
    ``None`` without a device track, ``frac=None`` when no
    collective-permute exists in the capture (pp=1 — nothing to hide).
    """
    return gather_overlap_fraction(trace_dir,
                                   names=("collective-permute",),
                                   window=window)


def differential_from_trace(trace_dir: str, n_short: int, n_long: int,
                            runs: int = 1,
                            is_program=None) -> float:
    """Device-side per-op slope from a trace holding alternating
    short/long chain executions.

    The trace must contain ``2 * runs`` program-execution device events
    in (short, long) launch order — :func:`validate_differential`'s
    capture loop produces exactly that. Slope =
    mean(dur_long - dur_short) / (n_long - n_short): the same
    constant-cost cancellation as the host-side differential, computed
    purely from XLA's device timeline.

    ``is_program``: predicate selecting executable-run events among the
    top-level ones. The device track also carries top-level op events
    on a second thread, ``copy-start``/``copy-done`` transfers, and —
    the subtle one — the readback fence's own tiny jitted helpers
    (``jit_ravel``/``jit_dynamic_slice``/``jit_squeeze``), which run
    once per fence, i.e. ``2 * runs`` times. The two chain modules are
    therefore identified *by occurrence count*: group the program
    events by full module name (XLA names runs ``jit_<fn>(<module
    id>)``, so the two chain lengths compile to two distinct names) and
    keep the groups seen exactly ``runs`` times; the longer-mean group
    is the longer chain. This is robust to launch-order interleaving
    and to whatever the fence lowers to.

    Multi-device traces record every program once per device track;
    counting across all tracks would see ``runs * n_devices``
    occurrences and match nothing. Only the lowest device pid's events
    are counted — any single device's program duration spans the whole
    (synchronized) collective, and the occurrence arithmetic then
    matches the single-chip case exactly.
    """
    if is_program is None:
        is_program = lambda name: name.startswith("jit")  # noqa: E731
    tops = [t for t in device_top_level_events(trace_dir)
            if is_program(t.name)]
    if tops:
        pid0 = min(t.pid for t in tops)
        tops = [t for t in tops if t.pid == pid0]
    groups: dict = {}
    for t in tops:
        groups.setdefault(t.name, []).append(t.dur)
    cands = {n: ds for n, ds in groups.items() if len(ds) == runs}
    if len(cands) != 2:
        raise ValueError(
            f"trace has {len(cands)} top-level device program groups "
            f"with {runs} runs (of {len(tops)} jit events total); need "
            "exactly 2 (the short and long chains) — wrong trace or a "
            "platform that records no device track"
        )
    means = sorted(sum(ds) / len(ds) for ds in cands.values())
    return (means[1] - means[0]) / (n_long - n_short)


def _slope_verdict(host_per_op_s, device_per_op_s, ratio, tol,
                   note) -> Optional[bool]:
    """Shared host-vs-device slope verdict — the ONE implementation
    behind both :class:`TimingValidation.ok` and
    :class:`HeadlineMeasurement.ok` so the CLI validate-timing verdict
    and the headline-measurement verdict cannot drift apart.

    - no device track: ``note`` set (track present but slope not
      extractable — a failure on the hardware the check exists for) →
      False; otherwise unjudged (None — the CPU test mesh).
    - degenerate device slope → False.
    - degenerate *host* slope next to a healthy device slope →
      unjudged (None): the relay's clock cannot resolve a few-µs
      per-op time (observed live: a 4 MiB VMEM-resident loopback
      reads 0.000 host vs 3.544 device µs/op), which is the
      diagnostic failing, not the device number — branding it a
      MISMATCH would let noise refute the published value.
    - else: the ratio band.
    """
    if device_per_op_s is None:
        return False if note else None
    if not device_per_op_s > 0:
        return False
    if not host_per_op_s > 0:  # NaN or nonpositive diagnostic
        return None
    return (1.0 / tol) <= ratio <= tol


@dataclass
class TimingValidation:
    host_per_op_s: float
    device_per_op_s: Optional[float]  # None: platform records no track
    ratio: Optional[float]
    tol: float
    n_short: int
    n_long: int
    # Set when a device track exists but the slope could not be
    # extracted from it (ambiguous program grouping): that is a
    # FAILURE on the hardware this check exists for, not "unjudged".
    note: Optional[str] = None

    @property
    def ok(self) -> Optional[bool]:
        """See :func:`_slope_verdict`."""
        return _slope_verdict(self.host_per_op_s, self.device_per_op_s,
                              self.ratio, self.tol, self.note)

    def describe(self) -> str:
        if self.device_per_op_s is None:
            if self.note:
                return ("timing-validation[MISMATCH]: device track "
                        f"present but slope not extractable — {self.note}")
            return ("timing-validation: no device track in trace "
                    "(platform records host events only) — not judged")
        ratio = f"{self.ratio:.3f}" if self.ratio is not None else "n/a"
        if self.ok is None:
            return (
                "timing-validation[UNJUDGED]: host differential "
                f"degenerate ({self.host_per_op_s * 1e6:.3f} us/op — "
                "relay clock cannot resolve this per-op time); "
                f"device-trace {self.device_per_op_s * 1e6:.3f} us/op "
                "stands"
            )
        verdict = "OK" if self.ok else "MISMATCH"
        return (
            f"timing-validation[{verdict}]: host-differential "
            f"{self.host_per_op_s * 1e6:.3f} us/op vs device-trace "
            f"{self.device_per_op_s * 1e6:.3f} us/op "
            f"(ratio {ratio}, tol {self.tol}x, "
            f"chains {self.n_short}/{self.n_long})"
        )


def validate_differential(
    make_chain: Callable[[int], Callable],
    x,
    iters: int,
    *,
    trace_dir: str,
    tol: float = 2.0,
    repeats: int = 3,
    runs: int = 2,
    timing=None,
) -> TimingValidation:
    """Measure host-differential AND device-trace slopes; compare.

    1. ``timing.measure_differential`` over ``make_chain`` — the host
       number every benchmark in this framework publishes.
    2. The same two compiled chains executed ``runs`` more times inside
       ``jax.profiler.trace(trace_dir)``; the device track's top-level
       event durations give the device-side slope with the same
       constant-cost cancellation.

    ``tol``: acceptance band for device/host ratio. The default 2x is
    deliberately loose — host timing through the axon relay carries
    session-dependent jitter (see BASELINE.md relay-variance note);
    the check exists to catch *category* errors (fence lies, compile
    time in the timed region, XLA caching a chain away), which show up
    as orders of magnitude, not tens of percent.
    """
    import jax

    from tpu_p2p.utils import timing as timing_mod

    timing = timing or timing_mod
    s = timing.measure_differential(make_chain, x, iters, repeats=repeats)
    short = max(1, iters // 8)
    if short >= iters:
        iters = short + 1
    f_short, f_long = make_chain(short), make_chain(iters)
    fence = timing_mod.readback_fence
    fence(f_short(x))  # both compiled before the trace starts
    fence(f_long(x))
    with jax.profiler.trace(trace_dir):
        for _ in range(runs):
            fence(f_short(x))
            fence(f_long(x))
    note = None
    try:
        dev = differential_from_trace(trace_dir, short, iters, runs=runs)
    except ValueError as e:
        dev = None
        # A track with events that merely defeat the grouping is a
        # failed validation, not an absent platform capability.
        if device_top_level_events(trace_dir):
            note = str(e)
    host = s.mean_region
    ratio = (dev / host) if (dev is not None and host > 0) else None
    return TimingValidation(
        host_per_op_s=host, device_per_op_s=dev, ratio=ratio, tol=tol,
        n_short=short, n_long=iters, note=note,
    )


def one_op_program_p50(f, x, runs: int = 48, timeout_s=None):
    """p50 device-timeline span of a whole single-op program —
    the dispatch-inclusive latency analogue.

    The scan-floor latency (``loopback_chain`` slope) deliberately
    measures only the scan *body*: no launch, no program setup, no
    drain. The reference's per-message metric is the opposite — it
    includes send-launch overhead and a full drain per message
    (`/root/reference/p2p_matrix.cc:153-177`, SURVEY §3.3 calls it
    "latency-inclusive"). This measures that: ``f`` compiles to one
    executable containing exactly one op; every execution's top-level
    device span is collected from one trace capture and the p50
    published. Spans are execution durations (queue wait excluded), so
    back-to-back enqueue is fine; one fence after the last call orders
    the trace close behind the final program on the stream.

    Returns ``(p50_seconds, n_spans)`` or ``(None, 0)`` when the
    platform records no device track (CPU test meshes). The target
    program is identified by occurrence count — the fence's own jitted
    helpers appear once, the target ``runs`` times.
    """
    import statistics as stats
    import tempfile
    from collections import Counter

    import jax

    from tpu_p2p.utils import timing as timing_mod

    out = f(x)
    timing_mod.run_fenced(out, timeout_s)  # compile + warm, untraced
    with tempfile.TemporaryDirectory(prefix="oneop_") as td:
        with jax.profiler.trace(td):
            # Fence every 8 runs: spans exclude queue wait either way,
            # but a deep queue of collective programs can starve a
            # participant thread on the in-process CPU backend past
            # XLA's 40 s rendezvous limit — a CHECK-fail abort, not an
            # exception (measured: 48 queued 8-device ppermutes under
            # machine load). Chunking also keeps the fence helpers'
            # occurrence count well below the target's.
            for i in range(runs):
                out = f(x)
                if (i + 1) % 8 == 0:
                    timing_mod.run_fenced(out, timeout_s)
            timing_mod.run_fenced(out, timeout_s)
        evs = device_top_level_events(td)
    if not evs:
        return None, 0
    # Lowest device pid only: multi-device traces record every program
    # once per track, which would inflate the published span count by
    # the device count (same rule as differential_from_trace).
    pid0 = min(e.pid for e in evs)
    evs = [e for e in evs if e.pid == pid0]
    counts = Counter(e.name for e in evs)
    name, _ = counts.most_common(1)[0]
    durs = [e.dur for e in evs if e.name == name]
    return float(stats.median(durs)), len(durs)


@dataclass
class HeadlineMeasurement:
    """A differential measurement whose published value prefers the
    device-trace slope over the host slope.

    The round-2 verdict's first finding: the framework computed both
    slopes but published the host one, which carries the relay's 2-3x
    session noise, so ``BENCH_r02.json`` contained a device-proven
    657 GB/s next to a published 346 GB/s. The fix is structural —
    the headline IS the device number whenever XLA records a device
    track (the north star's "``cudaEvent_t`` timing becomes XLA
    device-event timing"), and the host slope is demoted to the
    diagnostic. The two can no longer contradict: the validation
    fields and the published value come from the same measurement.
    """

    per_op_s: Optional[float]  # the number to publish, or None
    source: str  # "device_trace" | "host_differential" | "none"
    host_per_op_s: float
    device_per_op_s: Optional[float]
    ratio: Optional[float]  # device / host
    tol: float
    n_short: int
    n_long: int
    remeasured: bool = False  # True: first capture disagreed, re-ran
    note: Optional[str] = None
    timed_out: bool = False
    host_samples: Optional[object] = None  # the timing.Samples behind host

    @property
    def ok(self) -> Optional[bool]:
        """Verdict on host/device agreement.

        Mostly :class:`TimingValidation` semantics, with one asymmetry:
        a degenerate *host* slope (NaN / nonpositive — a noisy relay
        period can flip a thin differential negative) next to a healthy
        device slope is **unjudged** (None), not a failure. The device
        number is the published one; branding it "validation failed"
        because the diagnostic was noise would reintroduce the
        self-refuting artifact this class exists to prevent.
        (Shared implementation: :func:`_slope_verdict`.)
        """
        return _slope_verdict(self.host_per_op_s, self.device_per_op_s,
                              self.ratio, self.tol, self.note)

    def as_samples(self):
        """Adapter to the :class:`tpu_p2p.utils.timing.Samples` shape
        the workload plumbing consumes (``--mode device``): one sample
        holding the published per-op time, with the chosen ``source``
        riding along for cell records. Kept here so the two device-mode
        call sites (measure_collective, the latency per-hop estimate)
        cannot drift."""
        from tpu_p2p.utils import timing

        s = timing.Samples()
        s.timed_out = self.timed_out
        if self.per_op_s is not None:
            s.iter_seconds = [self.per_op_s]
            s.region_seconds = self.per_op_s
        s.source = self.source  # dynamic attr, read by cell_record
        return s

    def validation_fields(self) -> dict:
        """JSON-ready ``timing_validation`` dict — derived from the
        same run as the headline, so the artifact cannot refute its
        own number (round-2 verdict weak #1)."""
        h = self.host_per_op_s
        return {
            "ok": self.ok,
            "host_us_per_op": (
                round(h * 1e6, 4) if h == h else None
            ),
            "device_us_per_op": (
                round(self.device_per_op_s * 1e6, 4)
                if self.device_per_op_s is not None else None
            ),
            "ratio": round(self.ratio, 3) if self.ratio is not None else None,
            "headline_source": self.source,
            "remeasured": self.remeasured,
        }


def measure_headline(
    make_chain: Callable[[int], Callable],
    x,
    iters: int,
    *,
    repeats: int = 3,
    runs: int = 2,
    retol: float = 1.3,
    tol: float = 2.0,
    timing=None,
    timeout_s=None,
    barrier=None,
) -> HeadlineMeasurement:
    """Differential measurement publishing the device-trace slope.

    1. Compile the short/long chains once (``make_chain`` may build a
       fresh jit per call — both measurements below reuse the same
       compiled pair, so neither re-traces).
    2. Host differential via :func:`timing.measure_differential` —
       the diagnostic number.
    3. ``runs`` alternating (short, long) executions inside
       ``jax.profiler.trace``; the device track's top-level program
       durations give the device slope with the same constant-cost
       cancellation but none of the host/relay jitter.
    4. If both slopes exist and disagree beyond ``retol`` (1.3x), the
       whole measurement re-runs once — interleaved in time, so a
       transient relay stall cannot freeze a bad host number into the
       diagnostic. Mutually consistent device captures are averaged;
       otherwise the capture whose own host pair agrees wins (a
       corrupted capture must not bleed into the published number).
       Multi-process runs broadcast rank 0's re-measure decision so
       every rank takes the same branch (the chains are global
       collectives; a split decision would deadlock).

    The published ``per_op_s`` is the device slope when a device track
    exists (TPU), else the host slope (the simulated CPU mesh records
    host events only). ``source`` says which.
    """
    import tempfile

    import jax

    from tpu_p2p.utils import timing as timing_mod

    timing = timing or timing_mod
    short = max(1, iters // 8)
    if short >= iters:
        iters = short + 1
    f_short, f_long = make_chain(short), make_chain(iters)
    pre = {short: f_short, iters: f_long}

    def host_slope():
        return timing.measure_differential(
            lambda k: pre[k], x, iters, repeats=repeats,
            timeout_s=timeout_s, barrier=barrier,
        )

    def device_slope():
        # Same watchdog contract as the host half: a wedged link must
        # raise TransferTimeout here too, or --timeout would guard only
        # half of a device-mode measurement.
        def fence(v):
            timing_mod.run_fenced(v, timeout_s)

        with tempfile.TemporaryDirectory(prefix="headline_") as td:
            with jax.profiler.trace(td):
                for _ in range(runs):
                    fence(f_short(x))
                    fence(f_long(x))
            try:
                return differential_from_trace(td, short, iters,
                                               runs=runs), None
            except ValueError as e:
                # Events present but the grouping failed: a judgement
                # failure on real hardware. No events at all: the
                # platform records no device track (CPU) — unjudged.
                return None, (str(e) if device_top_level_events(td)
                              else None)
            except Exception as e:  # pragma: no cover - defensive
                return None, f"trace capture failed: {e!r}"

    def any_rank(flag: bool) -> bool:
        # Every early-exit-vs-continue fork below must be taken by ALL
        # ranks or none: the chains (and the broadcast further down)
        # are global collectives, so a rank departing alone strands
        # the rest. Timeouts cascade — a rank abandoning a chain wedges
        # the others in it until their own watchdogs fire — so every
        # rank does reach this sync point; the allgather then makes
        # the *decision* uniform (any rank wedged → everyone returns
        # the marked cell).
        if jax.process_count() <= 1:
            return flag
        import numpy as _np
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(_np.asarray(flag))
        return bool(_np.any(flags))

    s = host_slope()
    if any_rank(s.timed_out):
        return HeadlineMeasurement(
            per_op_s=None, source="none",
            # NaN whenever the COLLECTIVE verdict is timed_out: when a
            # peer's timeout forces this return, the local rank's own
            # host slope may be real, but the measurement as a whole is
            # a marked cell — publishing a live-looking slope under
            # timed_out=True would let ranks disagree about what the
            # field means (advisor r4 #4).
            host_per_op_s=float("nan"),
            device_per_op_s=None, ratio=None, tol=tol, n_short=short,
            n_long=iters, timed_out=True, host_samples=s,
        )
    from tpu_p2p.utils.errors import TransferTimeout

    host = s.mean_region
    dev_timed_out = False
    try:
        dev, note = device_slope()
    except TransferTimeout:
        # Wedged mid-capture: the whole measurement is a marked cell.
        dev, note, dev_timed_out = None, None, True
    if any_rank(dev_timed_out):
        return HeadlineMeasurement(
            # Same policy as the host-timeout return above: timed_out
            # publishes no slopes, even though the host half completed
            # here — a marked cell carries no live-looking numbers.
            per_op_s=None, source="none", host_per_op_s=float("nan"),
            device_per_op_s=None, ratio=None, tol=tol, n_short=short,
            n_long=iters, timed_out=True, host_samples=s,
        )
    remeasured = False
    want_remeasure = bool(
        dev is not None and host > 0
        and not ((1.0 / retol) <= dev / host <= retol)
    )
    if jax.process_count() > 1:
        # Host slopes carry rank-local relay jitter, so ranks can
        # disagree on want_remeasure — and the chains below run global
        # collectives: a rank-local decision would send only SOME
        # ranks back into them and deadlock the job at the first
        # ppermute. Broadcast rank 0's decision so every rank takes
        # the same branch (advisor r3 #1). Unconditional — gating the
        # broadcast on the local decision would itself desynchronize.
        import numpy as _np
        from jax.experimental import multihost_utils
        want_remeasure = bool(
            multihost_utils.broadcast_one_to_all(
                _np.asarray(want_remeasure))
        )
    if want_remeasure:
        # Disagreement beyond the re-measure band: one of the two
        # caught a bad period. Re-run both, interleaved in time, and
        # pick the device slope by which capture its own host pair
        # vouches for (advisor r3 #4: averaging in a corrupted first
        # capture retains half its error).
        s2 = host_slope()
        try:
            dev2, note2 = device_slope()
        except TransferTimeout:
            dev2, note2 = None, "re-measure capture timed out"
        remeasured = True
        if dev2 is not None:
            host2 = s2.mean_region if not s2.timed_out else float("nan")
            pair2_ok = (
                host2 == host2 and host2 > 0
                and (1.0 / retol) <= dev2 / host2 <= retol
            )
            captures_consistent = (
                dev is not None and dev > 0
                and (1.0 / retol) <= dev2 / dev <= retol
            )
            if dev is None:
                # This rank's first capture failed but a peer's
                # disagreement forced the re-measure (the broadcast
                # overrides the local gate): the fresh capture is the
                # only one there is.
                dev = dev2
            elif captures_consistent:
                # Both captures bound the truth: average.
                dev = (dev + dev2) / 2.0
            elif pair2_ok:
                # The fresh capture agrees with its own host pair and
                # the first didn't — the first capture was the
                # corrupted one (stall/recompile in-window).
                dev = dev2
            else:
                # No agreement signal at all. Corruption (a stall or a
                # recompile caught in-window) only ever inflates
                # device time, so the smaller capture is the cleaner.
                dev = min(dev, dev2)
        # The re-measure's note replaces the first capture's whenever
        # the re-measure produced a value OR a diagnosis: a successful
        # dev2 clears a stale "trace capture failed" from a first
        # capture the published number no longer rests on, and
        # "re-measure capture timed out" is the one signal that a
        # published first-capture slope was never re-confirmed. Only a
        # silent no-track re-measure (dev2 None, note2 None) keeps the
        # original note.
        if dev2 is not None or note2 is not None:
            note = note2
        if not s2.timed_out and s2.mean_region == s2.mean_region:
            host = s2.mean_region
            s = s2  # host_samples must match the reported host slope
    ratio = (dev / host) if (dev is not None and host > 0) else None
    if dev is not None and dev > 0:
        per_op, source = dev, "device_trace"
    elif host == host and host > 0:
        per_op, source = host, "host_differential"
    else:
        per_op, source = None, "none"
    return HeadlineMeasurement(
        per_op_s=per_op, source=source, host_per_op_s=host,
        device_per_op_s=dev, ratio=ratio, tol=tol, n_short=short,
        n_long=iters, remeasured=remeasured, note=note, host_samples=s,
    )
