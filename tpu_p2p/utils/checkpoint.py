"""Checkpoint / resume for model state.

SURVEY.md §5 "checkpoint / resume": the reference has none (its whole
sweep just reruns, ``p2p_matrix.cc`` start to finish). The benchmark
side of this framework already checkpoints per-cell via the JSONL
twin of the stdout matrix (:mod:`tpu_p2p.utils.report`); this module
adds the *model* side so training workloads (flagship / pipeline /
ring transformer) can save and restore sharded params.

Design: orbax-checkpoint when available (the idiomatic JAX answer —
async-capable, multi-host aware), with a plain ``.npz`` fallback that
has zero extra dependencies. Both paths round-trip arbitrary flat
``dict[str, Array]`` pytrees and re-place them onto a target mesh via
``NamedSharding``, so a checkpoint written under one mesh shape can be
restored under another (the resharding is a ``device_put``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

Params = Dict[str, jax.Array]

_META = "tpu_p2p_checkpoint.json"


def save_params(path: str, params: Params, step: int = 0) -> str:
    """Write ``params`` (+ step metadata) under directory ``path``.

    Host-gathers each leaf (``np.asarray``) and writes one ``.npz`` —
    simple, dependency-free, and correct for single-process use; the
    orbax path (:func:`save_params_orbax`) covers multi-host.
    """
    os.makedirs(path, exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in params.items()}
    np.savez(os.path.join(path, "params.npz"), **arrays)
    with open(os.path.join(path, _META), "w") as fh:
        json.dump(
            {"step": step, "keys": sorted(arrays),
             "dtypes": {k: str(v.dtype) for k, v in arrays.items()}},
            fh,
        )
    return path


def load_params(path: str, mesh: Optional[Mesh] = None,
                specs: Optional[dict] = None):
    """Restore ``(params, step)``; re-place onto ``mesh`` if given.

    ``specs``: ``{name: PartitionSpec}`` as produced by the model's
    ``*_param_specs(mesh)`` — restoring under a different mesh shape
    than the save is fine; placement is just a ``device_put``.
    """
    import ml_dtypes  # noqa: F401 — registers bfloat16 etc. with numpy

    with open(os.path.join(path, _META)) as fh:
        meta = json.load(fh)
    with np.load(os.path.join(path, "params.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    # npz stores extension dtypes (bfloat16, fp8) as raw void bytes;
    # re-view them through the dtype recorded at save time.
    for k, want in meta.get("dtypes", {}).items():
        if k in arrays and str(arrays[k].dtype) != want:
            arrays[k] = arrays[k].view(np.dtype(want))
    if set(arrays) != set(meta["keys"]):
        raise ValueError(
            f"checkpoint at {path} is torn: meta lists {meta['keys']}, "
            f"npz holds {sorted(arrays)}"
        )
    if mesh is not None and specs is not None:
        params = {
            k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in arrays.items()
        }
    else:
        params = {k: jax.numpy.asarray(v) for k, v in arrays.items()}
    return params, meta.get("step", 0)


_OPT_META = "tpu_p2p_opt_state.json"


def save_opt_state(path: str, opt_state, step: int = 0) -> str:
    """Write an optimizer-state pytree (any structure) under ``path``.

    Leaves are host-gathered and stored positionally (flatten order);
    :func:`load_opt_state` restores them into a freshly-initialized
    *template* state, which supplies structure and shardings — the
    same contract as params resume (same config ⇒ same tree).
    """
    os.makedirs(path, exist_ok=True)
    flat, _ = jax.tree_util.tree_flatten_with_path(opt_state)
    leaves = [v for _, v in flat]
    arrays = {f"l{i}": np.asarray(v) for i, v in enumerate(leaves)}
    np.savez(os.path.join(path, "opt_state.npz"), **arrays)
    with open(os.path.join(path, _OPT_META), "w") as fh:
        json.dump(
            {"step": step, "count": len(leaves),
             # Pairing fingerprint: leaves are stored positionally, so
             # two same-shaped leaves swapped by a different optax
             # version's tree order (mu vs nu) would otherwise restore
             # silently mis-paired. Per-leaf key paths name exactly
             # which slot each array came from (and unlike the full
             # PyTreeDef repr they don't encode node internals whose
             # rendering shifts across JAX versions).
             "leaf_paths": [jax.tree_util.keystr(kp) for kp, _ in flat],
             "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
             "shapes": {k: list(v.shape) for k, v in arrays.items()}},
            fh,
        )
    return path


def clear_opt_state(path: str) -> None:
    """Remove any optimizer-state files under ``path`` — the plain-sgd
    save path calls this so overwriting a rolling checkpoint dir never
    leaves a stale ``opt_state.npz`` paired with newer params."""
    for name in ("opt_state.npz", _OPT_META):
        fp = os.path.join(path, name)
        if os.path.exists(fp):
            os.remove(fp)


def load_opt_state(path: str, template, expect_step: Optional[int] = None):
    """Restore an optimizer state saved by :func:`save_opt_state` into
    ``template``'s structure and placements (``template`` = the state
    ``init_optimizer`` builds for the *same* optimizer and params).

    ``expect_step``: the params checkpoint's step — params and
    optimizer state are separate files, so a crash between the two
    saves (or a dir reused across optimizers) can leave a stale
    pairing; the recorded step makes that detectable."""
    import ml_dtypes  # noqa: F401 — registers bfloat16 etc. with numpy

    with open(os.path.join(path, _OPT_META)) as fh:
        meta = json.load(fh)
    if expect_step is not None and meta.get("step") != expect_step:
        raise ValueError(
            f"optimizer state at {path} was saved at step "
            f"{meta.get('step')}, but the params checkpoint is at step "
            f"{expect_step} — stale/torn optimizer state"
        )
    with np.load(os.path.join(path, "opt_state.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    for k, want in meta.get("dtypes", {}).items():
        if k in arrays and str(arrays[k].dtype) != want:
            arrays[k] = arrays[k].view(np.dtype(want))
    t_flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    t_leaves = [v for _, v in t_flat]
    if len(t_leaves) != meta["count"] or len(arrays) != meta["count"]:
        raise ValueError(
            f"optimizer state at {path} has {meta['count']} leaves; "
            f"this optimizer/config expects {len(t_leaves)} — "
            "optimizer/checkpoint mismatch"
        )
    saved_paths = meta.get("leaf_paths")  # absent in pre-r2 checkpoints
    if saved_paths is not None:
        want_paths = [jax.tree_util.keystr(kp) for kp, _ in t_flat]
        if saved_paths != want_paths:
            moved = [f"slot {i}: saved {s!r} vs expected {w!r}"
                     for i, (s, w) in enumerate(zip(saved_paths, want_paths))
                     if s != w][:4]
            raise ValueError(
                f"optimizer state at {path} pairs its leaves differently "
                f"than this optimizer/config ({'; '.join(moved)}) — "
                "positional restore would silently mis-pair same-shaped "
                "leaves (e.g. mu vs nu); refusing"
            )
    out = []
    for i, t in enumerate(t_leaves):
        a = arrays[f"l{i}"]
        if tuple(a.shape) != tuple(np.shape(t)):
            raise ValueError(
                f"optimizer leaf {i}: saved shape {a.shape} vs expected "
                f"{np.shape(t)} — optimizer/checkpoint mismatch"
            )
        sharding = getattr(t, "sharding", None)
        out.append(jax.device_put(a, sharding) if sharding is not None
                   else jax.numpy.asarray(a))
    return jax.tree.unflatten(treedef, out)


def save_params_orbax(path: str, params: Params, step: int = 0) -> str:
    """Orbax save — multi-host safe, async-capable. Falls back to
    :func:`save_params` when orbax is unavailable."""
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        return save_params(path, params, step)
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, f"step_{step}"), params)
    with open(os.path.join(path, _META), "w") as fh:
        json.dump({"step": step, "format": "orbax"}, fh)
    return path


def load_params_orbax(path: str, template: Params, step: int = 0) -> Params:
    """Orbax restore against a sharded ``template`` (abstract or
    concrete arrays carrying the target shardings).

    Mirrors :func:`save_params_orbax`'s fallback: a checkpoint written
    on an orbax-less host is an npz (meta lacks ``format: orbax``) and
    is loaded through :func:`load_params`, re-placed onto the
    template's shardings.
    """
    path = os.path.abspath(path)
    with open(os.path.join(path, _META)) as fh:
        meta = json.load(fh)
    if meta.get("format") != "orbax":
        params, have_step = load_params(path)
        if have_step != step:
            raise ValueError(
                f"checkpoint at {path} holds step {have_step}, "
                f"not the requested step {step}"
            )
        return {
            k: jax.device_put(v, template[k].sharding)
            if hasattr(template[k], "sharding") else v
            for k, v in params.items()
        }
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(
            os.path.join(path, f"step_{step}"),
            jax.tree.map(ocp.utils.to_shape_dtype_struct, template),
        )
