"""Checkpoint / resume for model state — durable, multi-generation.

SURVEY.md §5 "checkpoint / resume": the reference has none (its whole
sweep just reruns, ``p2p_matrix.cc`` start to finish). The benchmark
side of this framework already checkpoints per-cell via the JSONL
twin of the stdout matrix (:mod:`tpu_p2p.utils.report`); this module
adds the *model* side so training workloads (flagship / pipeline /
ring transformer) can save and restore sharded params.

Round 17 made the model side DURABLE (docs/checkpoint_durability.md).
The original layout — one rolling ``params.npz`` + meta overwritten
in place — is exactly the storage failure mode MegaScale (Jiang et
al., 2024) reports dominating real large-run downtime: a crash
mid-``np.savez`` leaves a truncated npz beside a stale-or-new meta
and the run is unrecoverable. The durable layout is generational:

- :func:`save_generation` writes a complete ``gen-<step>/`` (params,
  optional optimizer state + schedule metadata, and a ``MANIFEST.json``
  carrying per-file AND per-array sha256 checksums + byte sizes) into
  a temp dir, fsyncs every file and the directory, then publishes it
  with a single ``os.rename`` — a generation either exists completely
  or not at all. A ``LATEST`` pointer file is updated (write-temp +
  rename) only *after* publish, and the last K generations are
  retained (``keep``, default :data:`tpu_p2p.config.CKPT_KEEP`).
- :func:`load_latest` is the verifying loader: it walks generations
  newest-first, re-checking sizes and checksums
  (:func:`verify_generation` names the damage — torn manifest,
  truncated file, checksum mismatch, missing array, empty dir), and
  falls back generation by generation to the newest intact one,
  reporting what it skipped and why. ``train.py --resume`` /
  ``--heal`` / ``--supervise`` all route through it.
- Every generation file goes through an interposed writer that (a)
  retries transient ``OSError`` with bounded exponential backoff
  (:func:`tpu_p2p.utils.retry.retry_io`) and (b) applies the
  round-17 storage faults (:mod:`tpu_p2p.obs.faults`:
  ``ckpt_crash_after_bytes`` / ``ckpt_io_errors`` /
  ``ckpt_corrupt_seed``) — this module is on the fault grep-lint
  allowlist (tests/test_no_raw_collectives.py) as the ONLY storage
  application site.

The legacy flat layout (``params.npz`` + meta directly under the
directory) is still readable — :func:`load_latest` falls back to it
when no generation exists — and :func:`save_params` now records
per-array checksums in its meta so a torn flat pair (a crash between
the npz and meta writes leaving a new npz under an old meta, or vice
versa) is *detected* instead of silently loaded.

Design: orbax-checkpoint when available (the idiomatic JAX answer —
async-capable, multi-host aware), with the plain ``.npz`` layouts as
the zero-extra-dependency default. All paths round-trip arbitrary
flat ``dict[str, Array]`` pytrees and re-place them onto a target
mesh via ``NamedSharding``, so a checkpoint written under one mesh
shape can be restored under another (the resharding is a
``device_put``).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import shutil
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

Params = Dict[str, jax.Array]

_META = "tpu_p2p_checkpoint.json"
_OPT_META = "tpu_p2p_opt_state.json"
_SCHED_META = "train_schedule.json"
MANIFEST = "MANIFEST.json"
LATEST = "LATEST"
_GEN_FORMAT = "tpu-p2p-gen-1"
_GEN_RE = re.compile(r"^gen-(\d{6,})$")


def _gen_name(step: int) -> str:
    return f"gen-{int(step):06d}"


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _array_digest(a) -> str:
    return _digest(np.ascontiguousarray(a).tobytes())


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


# ----------------------------------------------- interposed IO writer
# Every generation file lands through _write_file: one choke point
# for fsync discipline, bounded retry, and the round-17 storage
# faults. Consulting faults.active_plan() here (and ONLY here, plus
# obs/faults.py itself) is pinned by the fault grep-lint.


def _io_session(step: int) -> dict:
    from tpu_p2p.obs import faults

    plan = faults.active_plan()
    return {
        "plan": plan,
        "step": int(step),
        "crash_budget": faults.ckpt_crash_budget(plan, step),
        "retries": 0,
        "bytes": 0,
    }


def _write_file(session: dict, path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` (flush + fsync), under the active
    fault plan's storage faults, retrying transient OSError with
    bounded exponential backoff."""
    from tpu_p2p.obs import faults
    from tpu_p2p.utils.retry import retry_io

    plan = session["plan"]

    def attempt():
        if faults.take_ckpt_io_error(plan):
            raise OSError(
                f"injected transient IO error writing {path} "
                "(FaultPlan.ckpt_io_errors)")
        budget = session["crash_budget"]
        with open(path, "wb") as fh:
            if budget is not None and len(data) > budget:
                fh.write(data[:budget])
                fh.flush()
                os.fsync(fh.fileno())
                faults.mark_ckpt_crash_fired(plan)
                crash = faults.SimulatedCrash(path, budget)
                crash.step = session["step"]
                raise crash
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        if budget is not None:
            session["crash_budget"] = budget - len(data)
        session["bytes"] += len(data)

    def count(_attempt, _exc):
        session["retries"] += 1

    retry_io(attempt, on_retry=count)


def _maybe_corrupt_published(session: dict, gen_dir: str) -> bool:
    """Apply the seeded published-generation bit flip
    (``FaultPlan.ckpt_corrupt_seed``) — the deterministic stand-in
    for at-rest rot, applied AFTER the atomic publish so the loader's
    checksum fallback (not the publish protocol) is what it tests."""
    from tpu_p2p.obs import faults

    plan = session["plan"]
    if not faults.ckpt_corrupt_due(plan, session["step"]):
        return False
    fp = os.path.join(gen_dir, "params.npz")
    with open(fp, "rb") as fh:
        data = bytearray(fh.read())
    rng = np.random.default_rng(plan.ckpt_corrupt_seed)
    off = int(rng.integers(0, len(data)))
    data[off] ^= 1 << int(rng.integers(0, 8))
    with open(fp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    return True


# ------------------------------------------------- payload assembly


def _params_payload(params: Params, step: int):
    """→ (npz_bytes, meta_dict, array_records) for a params dict."""
    arrays = {k: np.asarray(v) for k, v in params.items()}
    meta = {
        "step": int(step), "keys": sorted(arrays),
        "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
        # Per-array integrity: a torn npz/meta pair (or any bit rot)
        # must be detected, not loaded (round-17 satellite).
        "sha256": {k: _array_digest(v) for k, v in arrays.items()},
    }
    records = {
        k: {"sha256": meta["sha256"][k], "bytes": int(v.nbytes),
            "dtype": str(v.dtype), "shape": list(v.shape)}
        for k, v in arrays.items()
    }
    return _npz_bytes(arrays), meta, records


def _opt_payload(opt_state, step: int):
    """Flatten an optimizer-state pytree into the positional npz
    layout + its pairing-fingerprint meta (the structure contract
    :func:`load_opt_state` validates)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(opt_state)
    leaves = [np.asarray(v) for _, v in flat]
    arrays = {f"l{i}": v for i, v in enumerate(leaves)}
    meta = {
        "step": int(step), "count": len(leaves),
        # Pairing fingerprint: leaves are stored positionally, so
        # two same-shaped leaves swapped by a different optax
        # version's tree order (mu vs nu) would otherwise restore
        # silently mis-paired. Per-leaf key paths name exactly
        # which slot each array came from (and unlike the full
        # PyTreeDef repr they don't encode node internals whose
        # rendering shifts across JAX versions).
        "leaf_paths": [jax.tree_util.keystr(kp) for kp, _ in flat],
        "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
        "shapes": {k: list(v.shape) for k, v in arrays.items()},
    }
    records = {
        k: {"sha256": _array_digest(v), "bytes": int(v.nbytes),
            "dtype": str(v.dtype), "shape": list(v.shape)}
        for k, v in arrays.items()
    }
    return _npz_bytes(arrays), meta, records


# -------------------------------------------------- flat (legacy) API


def save_params(path: str, params: Params, step: int = 0) -> str:
    """Write ``params`` (+ step metadata) flat under directory
    ``path`` — the legacy single-checkpoint layout.

    Host-gathers each leaf (``np.asarray``) and writes one ``.npz``.
    The meta now carries per-array sha256 checksums, so a pair torn
    by a crash between the two writes is detected at load; for
    atomic multi-generation durability use :func:`save_generation`
    (the training loop does).
    """
    os.makedirs(path, exist_ok=True)
    npz, meta, _records = _params_payload(params, step)
    with open(os.path.join(path, "params.npz"), "wb") as fh:
        fh.write(npz)
    with open(os.path.join(path, _META), "w") as fh:
        json.dump(meta, fh)
    return path


def _load_flat_params(path: str) -> Tuple[Dict[str, np.ndarray], int]:
    """The verifying flat-layout reader shared by :func:`load_params`
    and the generation loader (a published generation's interior IS
    the flat layout plus a manifest)."""
    import ml_dtypes  # noqa: F401 — registers bfloat16 etc. with numpy

    with open(os.path.join(path, _META)) as fh:
        meta = json.load(fh)
    with np.load(os.path.join(path, "params.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    if set(arrays) != set(meta["keys"]):
        raise ValueError(
            f"checkpoint at {path} is torn: meta lists {meta['keys']}, "
            f"npz holds {sorted(arrays)}"
        )
    # Checksums verify on the RAW stored bytes (extension dtypes land
    # as void views; the bytes are dtype-independent), before the
    # dtype re-view below. Pre-round-17 metas lack the key and are
    # trusted as before.
    for k, want in meta.get("sha256", {}).items():
        if k not in arrays:
            continue  # key-set tears are already caught above
        got = _array_digest(arrays[k])
        if got != want:
            raise ValueError(
                f"checkpoint at {path} is torn: array {k!r} checksum "
                f"mismatch (npz and meta were written by different "
                "saves, or the file rotted at rest)"
            )
    # npz stores extension dtypes (bfloat16, fp8) as raw void bytes;
    # re-view them through the dtype recorded at save time.
    for k, want in meta.get("dtypes", {}).items():
        if k in arrays and str(arrays[k].dtype) != want:
            arrays[k] = arrays[k].view(np.dtype(want))
    return arrays, meta.get("step", 0)


def load_params(path: str, mesh: Optional[Mesh] = None,
                specs: Optional[dict] = None):
    """Restore ``(params, step)``; re-place onto ``mesh`` if given.

    ``specs``: ``{name: PartitionSpec}`` as produced by the model's
    ``*_param_specs(mesh)`` — restoring under a different mesh shape
    than the save is fine; placement is just a ``device_put``.

    When ``path`` holds generations, this routes through the
    verifying ladder (:func:`load_latest`) — the newest INTACT
    generation is what loads, corrupt ones are skipped. A flat legacy
    layout reads directly (with checksum verification when the meta
    carries checksums).
    """
    if list_generations(path):
        lc = load_latest(path)
        arrays, step = lc.params, lc.step
    else:
        arrays, step = _load_flat_params(path)
    if mesh is not None and specs is not None:
        params = {
            k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in arrays.items()
        }
    else:
        params = {k: jax.numpy.asarray(v) for k, v in arrays.items()}
    return params, step


def save_opt_state(path: str, opt_state, step: int = 0) -> str:
    """Write an optimizer-state pytree (any structure) under ``path``
    — the legacy flat layout (:func:`save_generation` folds the same
    files into the atomic generation publish instead).

    Leaves are host-gathered and stored positionally (flatten order);
    :func:`load_opt_state` restores them into a freshly-initialized
    *template* state, which supplies structure and shardings — the
    same contract as params resume (same config ⇒ same tree).
    """
    os.makedirs(path, exist_ok=True)
    npz, meta, _records = _opt_payload(opt_state, step)
    with open(os.path.join(path, "opt_state.npz"), "wb") as fh:
        fh.write(npz)
    with open(os.path.join(path, _OPT_META), "w") as fh:
        json.dump(meta, fh)
    return path


def clear_opt_state(path: str) -> None:
    """Remove any optimizer-state files under ``path`` — the plain-sgd
    save path calls this so overwriting a rolling checkpoint dir never
    leaves a stale ``opt_state.npz`` paired with newer params. (The
    generation layout needs no such sweep: each ``gen-<step>/`` is
    self-contained, published atomically with or without opt files.)"""
    for name in ("opt_state.npz", _OPT_META):
        fp = os.path.join(path, name)
        if os.path.exists(fp):
            os.remove(fp)


def load_opt_state(path: str, template, expect_step: Optional[int] = None):
    """Restore an optimizer state saved by :func:`save_opt_state` (or
    inside a generation dir — same files) into ``template``'s
    structure and placements (``template`` = the state
    ``init_optimizer`` builds for the *same* optimizer and params).

    ``expect_step``: the params checkpoint's step — in the legacy
    flat layout params and optimizer state are separate files, so a
    crash between the two saves (or a dir reused across optimizers)
    can leave a stale pairing; the recorded step makes that
    detectable. (Generations publish both atomically, so a mismatch
    there means a damaged manifest — also refused.)"""
    import ml_dtypes  # noqa: F401 — registers bfloat16 etc. with numpy

    with open(os.path.join(path, _OPT_META)) as fh:
        meta = json.load(fh)
    if expect_step is not None and meta.get("step") != expect_step:
        raise ValueError(
            f"optimizer state at {path} was saved at step "
            f"{meta.get('step')}, but the params checkpoint is at step "
            f"{expect_step} — stale/torn optimizer state"
        )
    with np.load(os.path.join(path, "opt_state.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    for k, want in meta.get("dtypes", {}).items():
        if k in arrays and str(arrays[k].dtype) != want:
            arrays[k] = arrays[k].view(np.dtype(want))
    t_flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    t_leaves = [v for _, v in t_flat]
    if len(t_leaves) != meta["count"] or len(arrays) != meta["count"]:
        raise ValueError(
            f"optimizer state at {path} has {meta['count']} leaves; "
            f"this optimizer/config expects {len(t_leaves)} — "
            "optimizer/checkpoint mismatch"
        )
    saved_paths = meta.get("leaf_paths")  # absent in pre-r2 checkpoints
    if saved_paths is not None:
        want_paths = [jax.tree_util.keystr(kp) for kp, _ in t_flat]
        if saved_paths != want_paths:
            moved = [f"slot {i}: saved {s!r} vs expected {w!r}"
                     for i, (s, w) in enumerate(zip(saved_paths, want_paths))
                     if s != w][:4]
            raise ValueError(
                f"optimizer state at {path} pairs its leaves differently "
                f"than this optimizer/config ({'; '.join(moved)}) — "
                "positional restore would silently mis-pair same-shaped "
                "leaves (e.g. mu vs nu); refusing"
            )
    out = []
    for i, t in enumerate(t_leaves):
        a = arrays[f"l{i}"]
        if tuple(a.shape) != tuple(np.shape(t)):
            raise ValueError(
                f"optimizer leaf {i}: saved shape {a.shape} vs expected "
                f"{np.shape(t)} — optimizer/checkpoint mismatch"
            )
        sharding = getattr(t, "sharding", None)
        out.append(jax.device_put(a, sharding) if sharding is not None
                   else jax.numpy.asarray(a))
    return jax.tree.unflatten(treedef, out)


# ------------------------------------------- durable generation layout


def list_generations(path: str) -> List[Tuple[int, str]]:
    """Published generations under ``path``, NEWEST FIRST, as
    ``(step, name)`` pairs — the fallback ladder's walk order. Only
    fully-renamed ``gen-<step>`` directories count; temp dirs from a
    crashed save never appear."""
    out: List[Tuple[int, str]] = []
    if not os.path.isdir(path):
        return out
    for name in os.listdir(path):
        m = _GEN_RE.match(name)
        if m and os.path.isdir(os.path.join(path, name)):
            out.append((int(m.group(1)), name))
    out.sort(reverse=True)
    return out


def has_checkpoint(path: Optional[str]) -> bool:
    """Is there anything restorable under ``path`` — a published
    generation or a legacy flat ``params.npz``? (Existence, not
    integrity: :func:`load_latest` judges intactness.)"""
    if not path:
        return False
    if list_generations(path):
        return True
    return os.path.exists(os.path.join(path, "params.npz"))


def read_latest_pointer(path: str) -> Optional[str]:
    """The ``LATEST`` pointer's generation name, or None. Updated
    only after a publish completes, so it always names a generation
    that finished its atomic rename — but the loader treats it as a
    hint and walks the full ladder regardless (a crash between
    publish and pointer update leaves a newer intact generation the
    pointer has not caught up to)."""
    fp = os.path.join(path, LATEST)
    try:
        with open(fp) as fh:
            name = fh.read().strip()
    except OSError:
        return None
    return name or None


def verify_generation(gen_dir: str) -> Optional[str]:
    """Integrity-check one published generation; → None when intact,
    else a reason string naming the damage (the fallback report's
    vocabulary: empty dir, missing/torn manifest, missing file,
    truncation, file/array checksum mismatch, missing array)."""
    if not os.path.isdir(gen_dir):
        return "missing generation dir"
    if not os.listdir(gen_dir):
        return "empty generation dir"
    mf = os.path.join(gen_dir, MANIFEST)
    if not os.path.exists(mf):
        return "missing manifest"
    try:
        with open(mf) as fh:
            manifest = json.load(fh)
    except (json.JSONDecodeError, OSError) as e:
        return f"torn manifest ({type(e).__name__})"
    if (manifest.get("format") != _GEN_FORMAT
            or not isinstance(manifest.get("files"), dict)
            or "step" not in manifest):
        return "torn manifest (wrong format/keys)"
    for fname, rec in sorted(manifest["files"].items()):
        fp = os.path.join(gen_dir, fname)
        if not os.path.exists(fp):
            return f"missing file {fname}"
        size = os.path.getsize(fp)
        if size != rec.get("bytes"):
            return (f"truncated {fname}: {size} of "
                    f"{rec.get('bytes')} bytes")
        with open(fp, "rb") as fh:
            if _digest(fh.read()) != rec.get("sha256"):
                return f"checksum mismatch in {fname}"
    for fname, want in sorted(manifest.get("arrays", {}).items()):
        fp = os.path.join(gen_dir, fname)
        try:
            with np.load(fp) as z:
                arrays = {k: z[k] for k in z.files}
        except Exception as e:  # noqa: BLE001 — any unreadable npz is
            # the same verdict: this generation cannot be trusted.
            return f"unreadable {fname} ({type(e).__name__})"
        missing = set(want) - set(arrays)
        if missing:
            return f"missing array {sorted(missing)[0]!r} in {fname}"
        extra = set(arrays) - set(want)
        if extra:
            return f"unexpected array {sorted(extra)[0]!r} in {fname}"
        for k, rec in sorted(want.items()):
            if _array_digest(arrays[k]) != rec.get("sha256"):
                return f"array checksum mismatch: {k!r} in {fname}"
    return None


@dataclass
class LoadedCheckpoint:
    """What the verifying loader found: the generation (or legacy
    flat dir) it settled on, host-side params, and the ladder of
    generations it skipped with the reason each was rejected."""

    path: str                 # the dir the params came from
    name: Optional[str]       # gen-XXXXXX, or None for legacy flat
    step: int
    params: Dict[str, np.ndarray]
    skipped: List[dict] = field(default_factory=list)


def load_latest(path: str) -> LoadedCheckpoint:
    """The verifying loader: walk generations newest-first, verify
    each (:func:`verify_generation`), and return the newest INTACT
    one — falling back to the legacy flat layout when no generation
    exists. Raises ``ValueError`` (listing every skipped generation
    and why) when nothing restorable survives."""
    skipped: List[dict] = []
    for _step, name in list_generations(path):
        gd = os.path.join(path, name)
        reason = verify_generation(gd)
        if reason is not None:
            skipped.append({"generation": name, "reason": reason})
            continue
        arrays, step = _load_flat_params(gd)
        return LoadedCheckpoint(path=gd, name=name, step=step,
                                params=arrays, skipped=skipped)
    if os.path.exists(os.path.join(path, "params.npz")):
        arrays, step = _load_flat_params(path)
        return LoadedCheckpoint(path=path, name=None, step=step,
                                params=arrays, skipped=skipped)
    detail = "; ".join(f"{s['generation']}: {s['reason']}"
                       for s in skipped) or "no generations, no flat layout"
    raise ValueError(
        f"no intact checkpoint under {path} ({detail})"
    )


def latest_intact_step(path: str) -> Optional[int]:
    """Step of the newest generation that verifies (legacy flat step
    when no generation exists), or None — the heal/supervisor paths'
    answer to "where would a resume land?" without loading params
    twice on failure."""
    for step, name in list_generations(path):
        if verify_generation(os.path.join(path, name)) is None:
            return step
    meta = os.path.join(path, _META)
    if os.path.exists(meta) and os.path.exists(
            os.path.join(path, "params.npz")):
        try:
            with open(meta) as fh:
                return int(json.load(fh).get("step", 0))
        except (json.JSONDecodeError, OSError, ValueError):
            return None
    return None


def save_generation(path: str, params: Params, step: int, *,
                    opt_state=None, sched_meta: Optional[dict] = None,
                    keep: Optional[int] = None) -> dict:
    """Atomically publish ``gen-<step>/`` under ``path``.

    Protocol (docs/checkpoint_durability.md): every file — params.npz,
    its meta, optional opt_state.npz + meta + schedule metadata, and
    the MANIFEST with per-file and per-array sha256 + byte sizes — is
    written into a hidden temp dir through the interposed fault/retry
    writer with flush+fsync, the temp dir is fsynced, ONE
    ``os.rename`` publishes it, the parent dir is fsynced, and only
    then is the ``LATEST`` pointer updated (write-temp + rename) and
    retention pruned to the newest ``keep`` generations (default
    :data:`tpu_p2p.config.CKPT_KEEP`). A crash at ANY byte leaves
    either no new generation (temp dirs are swept by the next save
    and never parse as generations) or a complete, verifiable one.

    Params and optimizer state publish in the SAME generation — the
    torn params@N/opt@N-1 pairing the two-file legacy save could
    produce cannot exist here.

    → a stats dict: ``path``/``name``/``step``/``bytes`` written,
    ``write_retries`` absorbed, ``corrupted`` (the injected rot
    fault, when it fired) and ``pruned`` generation names.
    """
    if keep is None:
        from tpu_p2p.config import CKPT_KEEP

        keep = CKPT_KEEP
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    os.makedirs(path, exist_ok=True)
    # Sweep leftovers from crashed saves (single-writer contract: one
    # training process owns a checkpoint dir).
    for name in os.listdir(path):
        if name.startswith((".tmp-gen-", ".stale-gen-")):
            shutil.rmtree(os.path.join(path, name), ignore_errors=True)

    session = _io_session(step)
    name = _gen_name(step)
    tmp = os.path.join(path, f".tmp-gen-{uuid.uuid4().hex[:8]}")
    os.makedirs(tmp)

    files: Dict[str, bytes] = {}
    arrays_manifest: Dict[str, dict] = {}
    npz, meta, records = _params_payload(params, step)
    files["params.npz"] = npz
    files[_META] = json.dumps(meta).encode()
    arrays_manifest["params.npz"] = records
    if opt_state is not None:
        onpz, ometa, orecords = _opt_payload(opt_state, step)
        files["opt_state.npz"] = onpz
        files[_OPT_META] = json.dumps(ometa).encode()
        arrays_manifest["opt_state.npz"] = orecords
    if sched_meta is not None:
        files[_SCHED_META] = json.dumps(sched_meta).encode()
    manifest = {
        "format": _GEN_FORMAT,
        "step": int(step),
        "files": {fname: {"sha256": _digest(data),
                          "bytes": len(data)}
                  for fname, data in files.items()},
        "arrays": arrays_manifest,
    }
    # The manifest covers every sibling file (it cannot list itself;
    # a torn manifest is caught by its own JSON parse + format keys).
    files[MANIFEST] = json.dumps(manifest, indent=1).encode()

    for fname in ("params.npz", _META, "opt_state.npz", _OPT_META,
                  _SCHED_META, MANIFEST):
        if fname in files:
            _write_file(session, os.path.join(tmp, fname),
                        files[fname])
    _fsync_dir(tmp)

    final = os.path.join(path, name)
    if os.path.exists(final):
        # Republishing a step (e.g. a resumed run re-reaching a save
        # point whose generation rotted): move the stale dir aside so
        # the rename stays atomic, then drop it.
        aside = os.path.join(path,
                             f".stale-gen-{uuid.uuid4().hex[:8]}")
        os.rename(final, aside)
        os.rename(tmp, final)
        shutil.rmtree(aside, ignore_errors=True)
    else:
        os.rename(tmp, final)
    _fsync_dir(path)

    # LATEST is updated ONLY after the publish rename — through the
    # same interposed writer, so a crash budget spanning the pointer
    # update leaves the previous pointer intact (and the loader walks
    # the ladder regardless).
    latest_tmp = os.path.join(path, LATEST + ".tmp")
    _write_file(session, latest_tmp, (name + "\n").encode())
    os.replace(latest_tmp, os.path.join(path, LATEST))
    _fsync_dir(path)

    corrupted = _maybe_corrupt_published(session, final)

    pruned: List[str] = []
    for _s, old in list_generations(path)[keep:]:
        shutil.rmtree(os.path.join(path, old), ignore_errors=True)
        pruned.append(old)

    return {"path": final, "name": name, "step": int(step),
            "bytes": session["bytes"],
            "write_retries": session["retries"],
            "corrupted": corrupted, "pruned": pruned}


# ----------------------------------------------------------- orbax


def save_params_orbax(path: str, params: Params, step: int = 0) -> str:
    """Orbax save — multi-host safe, async-capable. Falls back to
    :func:`save_params` when orbax is unavailable."""
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        return save_params(path, params, step)
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, f"step_{step}"), params)
    with open(os.path.join(path, _META), "w") as fh:
        json.dump({"step": step, "format": "orbax"}, fh)
    return path


def load_params_orbax(path: str, template: Params, step: int = 0) -> Params:
    """Orbax restore against a sharded ``template`` (abstract or
    concrete arrays carrying the target shardings).

    Mirrors :func:`save_params_orbax`'s fallback: a checkpoint written
    on an orbax-less host is an npz (meta lacks ``format: orbax``) and
    is loaded through :func:`load_params`, re-placed onto the
    template's shardings.
    """
    path = os.path.abspath(path)
    with open(os.path.join(path, _META)) as fh:
        meta = json.load(fh)
    if meta.get("format") != "orbax":
        params, have_step = load_params(path)
        if have_step != step:
            raise ValueError(
                f"checkpoint at {path} holds step {have_step}, "
                f"not the requested step {step}"
            )
        return {
            k: jax.device_put(v, template[k].sharding)
            if hasattr(template[k], "sharding") else v
            for k, v in params.items()
        }
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(
            os.path.join(path, f"step_{step}"),
            jax.tree.map(ocp.utils.to_shape_dtype_struct, template),
        )
