"""ctypes bindings to the native C++ support library.

The reference is a single natively-compiled C++ program
(``/root/reference/Makefile:2``). On TPU the data plane is XLA itself
(SURVEY.md §2.2 — re-linking NCCL has no analogue), so the native
surface that *remains* native here is the runtime support the C++
program got from libc/chrono for free and the hot host-side paths:

- monotonic nanosecond clock (``clock_gettime(CLOCK_MONOTONIC)``) —
  replaces the reference's ``std::chrono::system_clock`` reads
  (``p2p_matrix.cc:153,174``) with a step-free clock;
- DJB2a hostname hashing (bit-parity with ``getHostHash``,
  ``p2p_matrix.cc:44-51``) and hostname truncation (``:53-61``);
- sorting-based percentile/stat kernels over per-iteration samples
  (the reference keeps only a mean, ``:176``; BASELINE.json wants p50).

Built by ``make native`` into ``native/libtpu_p2p_native.so`` (see
``/root/repo/native/tpu_p2p_native.cc``). Loaded lazily; every entry
point has a pure-Python fallback so the framework runs unbuilt — the
bindings are ``ctypes`` because pybind11 is unavailable in this image.
"""

from __future__ import annotations

import ctypes
import os
import time
from typing import Optional, Sequence

_LIB_ENV = "TPU_P2P_NATIVE_LIB"
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _candidates():
    if os.environ.get(_LIB_ENV):
        yield os.environ[_LIB_ENV]
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    yield os.path.join(here, "native", "libtpu_p2p_native.so")


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    for path in _candidates():
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
            lib.tpu_p2p_monotonic_ns.restype = ctypes.c_uint64
            lib.tpu_p2p_djb2a.argtypes = [ctypes.c_char_p]
            lib.tpu_p2p_djb2a.restype = ctypes.c_uint64
            lib.tpu_p2p_host_hash.restype = ctypes.c_uint64
            lib.tpu_p2p_percentile.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_size_t,
                ctypes.c_double,
            ]
            lib.tpu_p2p_percentile.restype = ctypes.c_double
            lib.tpu_p2p_stats.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_double),
            ]
            lib.tpu_p2p_stats.restype = None
            lib.tpu_p2p_check_placement.argtypes = [
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int,
                ctypes.c_int,
            ]
            lib.tpu_p2p_check_placement.restype = ctypes.c_int
            lib.tpu_p2p_gbps.argtypes = [
                ctypes.c_uint64, ctypes.c_double, ctypes.c_int,
            ]
            lib.tpu_p2p_gbps.restype = ctypes.c_double
            lib.tpu_p2p_format_header.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.tpu_p2p_format_header.restype = ctypes.c_long
            lib.tpu_p2p_format_cell.argtypes = [
                ctypes.c_double, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.tpu_p2p_format_cell.restype = ctypes.c_long
            lib.tpu_p2p_format_row_label.argtypes = [
                ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.tpu_p2p_format_row_label.restype = ctypes.c_long
            _lib = lib
            break
        except OSError:
            continue
    return _lib


def available() -> bool:
    return _load() is not None


def monotonic_ns() -> int:
    lib = _load()
    if lib is not None:
        return int(lib.tpu_p2p_monotonic_ns())
    return time.perf_counter_ns()


def djb2a(s: str) -> int:
    lib = _load()
    if lib is not None:
        return int(lib.tpu_p2p_djb2a(s.encode()))
    from tpu_p2p.parallel.topology import djb2a_hash

    return djb2a_hash(s)


def host_hash() -> int:
    lib = _load()
    if lib is not None:
        return int(lib.tpu_p2p_host_hash())
    from tpu_p2p.parallel import topology

    return topology.host_hash()


def percentile(samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (matches timing.Samples.percentile)."""
    lib = _load()
    arr = (ctypes.c_double * len(samples))(*samples)
    if lib is not None and len(samples):
        return float(lib.tpu_p2p_percentile(arr, len(samples), q))
    import math

    s = sorted(samples)
    if not s:
        return math.nan
    rank = max(0, min(len(s) - 1, math.ceil(q / 100.0 * len(s)) - 1))
    return s[rank]


def check_placement(host_keys: Sequence[int], rank: int) -> int:
    """Local device id for ``rank``, or raise on a bad placement —
    native twin of :func:`tpu_p2p.parallel.topology.validate_placement`
    (the reference's ``check_process_placement_policy``,
    ``p2p_matrix.cc:63-100``). Both paths raise the same messages."""
    from tpu_p2p.parallel import topology
    from tpu_p2p.utils.errors import PlacementError

    if not 0 <= rank < len(host_keys):
        raise PlacementError(
            f"bad placement arguments: n={len(host_keys)}, {rank=}"
        )
    lib = _load()
    if lib is None:
        return topology.validate_placement(host_keys).local_id(rank)
    arr = (ctypes.c_uint64 * len(host_keys))(*host_keys)
    r = int(lib.tpu_p2p_check_placement(arr, len(host_keys), rank))
    if r == -1:
        raise PlacementError(topology._MSG_NONUNIFORM)
    if r == -2:
        raise PlacementError(topology._MSG_NONCONTIGUOUS)
    return r


def gbps(msg_bytes: int, seconds: float, bidir: bool = False) -> float:
    """Gbps = bytes*8/t/1e9, ×2 for bi-dir (``p2p_matrix.cc:177,258``).

    Native twin of :func:`tpu_p2p.utils.timing.gbps` (the production
    formula); the fallback delegates there so there is one source of
    truth per language."""
    lib = _load()
    if lib is not None:
        return float(lib.tpu_p2p_gbps(msg_bytes, seconds, int(bidir)))
    from tpu_p2p.utils import timing

    return timing.gbps(msg_bytes, seconds, directions=2 if bidir else 1)


def format_header(title: str, n: int) -> Optional[str]:
    """The matrix title + ``D\\D`` header line, natively formatted;
    None when the library is unbuilt (callers fall back to Python)."""
    lib = _load()
    if lib is None:
        return None
    # Sized from the title (not a fixed constant): the production title
    # is 55 chars, and a fixed 64-byte slack would silently fall back
    # to Python formatting the day the title grows.
    buf = ctypes.create_string_buffer(len(title.encode()) + 16 + 7 * n)
    w = lib.tpu_p2p_format_header(title.encode(), n, buf, len(buf))
    return buf.raw[:w].decode() if w > 0 else None


def format_cell(value: float) -> Optional[str]:
    """One ``%6.02f`` cell, natively formatted; None when unbuilt."""
    lib = _load()
    if lib is None:
        return None
    buf = ctypes.create_string_buffer(64)
    w = lib.tpu_p2p_format_cell(value, buf, len(buf))
    return buf.raw[:w].decode() if w > 0 else None


def format_row_label(src: int) -> Optional[str]:
    """One ``%6d`` row label, natively formatted; None when unbuilt."""
    lib = _load()
    if lib is None:
        return None
    buf = ctypes.create_string_buffer(64)
    w = lib.tpu_p2p_format_row_label(src, buf, len(buf))
    return buf.raw[:w].decode() if w > 0 else None


def stats(samples: Sequence[float]) -> dict:
    """{mean, min, max, p50, p99} in one native pass, or Python fallback."""
    import math

    if not samples:
        return {k: math.nan for k in ("mean", "min", "max", "p50", "p99")}
    lib = _load()
    if lib is not None:
        arr = (ctypes.c_double * len(samples))(*samples)
        out = (ctypes.c_double * 5)()
        lib.tpu_p2p_stats(arr, len(samples), out)
        return dict(zip(("mean", "min", "max", "p50", "p99"), out))
    s = sorted(samples)

    def nr(q):
        return s[max(0, min(len(s) - 1, math.ceil(q / 100.0 * len(s)) - 1))]

    return {
        "mean": sum(s) / len(s),
        "min": s[0],
        "max": s[-1],
        "p50": nr(50.0),
        "p99": nr(99.0),
    }
