"""L7 — reporting.

The human-readable matrix format is the reference's product contract
(SURVEY.md §5 "metrics/logging") and is reproduced byte-for-byte:

- section title then ``   D\\D`` header with ``%6d ``-formatted column
  ids (``/root/reference/p2p_matrix.cc:134-139,189-194``),
- ``%6d ``-formatted row label (``:143,198``),
- ``%6.02f ``-formatted Gbps cells, ``0.00`` on the diagonal
  (``:147-151,179,202-206,260``),
- a flush after every cell so a hung pair is visible live
  (``:180,261``),
- newline per row (``:183-185,264-266``).

Additions mandated by SURVEY.md §5/§6 (the reference never aggregates
or persists): a min/avg summary over the off-diagonal cells (the
BASELINE.json metric), and a JSONL record per cell — the
machine-readable twin of the per-cell ``fflush`` — which doubles as a
resume-by-skip checkpoint (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import json
import math
import os
import sys
from dataclasses import dataclass, field
from typing import IO, Optional

from tpu_p2p.utils import native as _native


class MatrixReporter:
    """Streams one N×N matrix in the reference's exact format."""

    def __init__(self, n: int, title: str, stream: Optional[IO] = None) -> None:
        self.n = n
        self.title = title
        self.stream = stream if stream is not None else sys.stdout
        self.values = [[math.nan] * n for _ in range(n)]

    def _w(self, text: str) -> None:
        self.stream.write(text)

    def header(self) -> None:
        # p2p_matrix.cc:134-139 — title line, then "   D\D" + "%6d " ids.
        # Once per matrix, so the native snprintf path (byte-equal to
        # the Python one — asserted in tests/test_native.py) runs here;
        # the per-cell hot path below stays direct %-formatting.
        text = _native.format_header(self.title, self.n)
        if text is None:
            text = f"{self.title}\n   D\\D" + "".join(
                "%6d " % i for i in range(self.n)
            ) + "\n"
        self._w(text)

    def row_label(self, src: int) -> None:
        self._w("%6d " % src)  # p2p_matrix.cc:143

    def cell(self, src: int, dst: int, value: float) -> None:
        # p2p_matrix.cc:179-181 — "%6.02f " then fflush for live progress.
        self.values[src][dst] = value
        self._w("%6.02f " % value)
        self.stream.flush()

    def diagonal(self, i: int) -> None:
        # p2p_matrix.cc:147-151 — the diagonal prints 0.00.
        self.cell(i, i, 0.0)

    def end_row(self) -> None:
        self._w("\n")  # p2p_matrix.cc:184

    # -- aggregation (additive; BASELINE.json "min/avg of the matrix") ----

    def off_diagonal(self):
        return [
            self.values[i][j]
            for i in range(self.n)
            for j in range(self.n)
            if i != j and not math.isnan(self.values[i][j])
        ]

    def summary(self) -> dict:
        cells = self.off_diagonal()
        if not cells:
            return {"min": math.nan, "avg": math.nan, "max": math.nan, "cells": 0}
        return {
            "min": min(cells),
            "avg": sum(cells) / len(cells),
            "max": max(cells),
            "cells": len(cells),
        }

    def print_summary(self, label: str) -> dict:
        s = self.summary()
        self._w(
            f"# {label}: min {s['min']:.2f}  avg {s['avg']:.2f}  "
            f"max {s['max']:.2f}  over {s['cells']} cells\n"
        )
        self.stream.flush()
        return s


def render_matrix(values, title: str,
                  stream: Optional[IO] = None) -> MatrixReporter:
    """Render a complete N×N matrix in one call.

    The streaming per-cell API above serves live sweeps (one flush per
    measured cell, p2p_matrix.cc:180); consumers that already hold the
    whole matrix — the obs ledger's trace-join
    (:mod:`tpu_p2p.obs.ledger`) — render it here in the identical
    byte format. NaN cells (links the ledger saw no traffic on) print
    as a field-width ``--`` and stay NaN in ``reporter.values``: a
    DEAD link measures ~0.00 and must stay distinguishable from an
    unmeasured one (the health engine's per-link detector reads this
    matrix — docs/health.md), and
    :meth:`MatrixReporter.summary` aggregates only measured links.
    ``None`` counts as unmeasured too (the JSON artifacts' NaN
    spelling).
    """
    n = len(values)
    rep = MatrixReporter(n, title, stream)
    rep.header()
    for src in range(n):
        rep.row_label(src)
        for dst in range(n):
            v = values[src][dst]
            if src == dst:
                rep.diagonal(src)
            elif v is None or math.isnan(v):
                rep._w("%6s " % "--")  # unmeasured; values[] stays NaN
            else:
                rep.cell(src, dst, v)
        rep.end_row()
    return rep


@dataclass
class CellRecord:
    """One measured cell — the JSONL twin of one ``%6.02f`` print."""

    workload: str
    direction: str
    src: int
    dst: int
    msg_bytes: int
    iters: int
    mode: str
    gbps: float
    mean_s: float = math.nan
    p50_s: float = math.nan
    p99_s: float = math.nan
    min_s: float = math.nan
    timed_out: bool = False
    hops: Optional[int] = None
    extra: dict = field(default_factory=dict)

    def key(self) -> tuple:
        return (self.workload, self.direction, self.src, self.dst,
                self.msg_bytes, self.mode)

    def to_json(self) -> str:
        d = dict(self.__dict__)
        extra = d.pop("extra")
        d.update(extra)
        return json.dumps(d, allow_nan=True)


class JsonlWriter:
    """Append-per-cell structured log; the resume checkpoint."""

    def __init__(self, path: Optional[str]) -> None:
        self.path = path
        self._fh = open(path, "a") if path else None

    def write(self, rec: CellRecord) -> None:
        if self._fh:
            self._fh.write(rec.to_json() + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


def load_done_cells(path: Optional[str]) -> dict:
    """Completed cells from a previous run's JSONL → {key: gbps}.

    Resume-by-skip (SURVEY.md §5 checkpoint/resume): a rerun with
    ``--resume`` replays finished cells from here instead of
    re-measuring — the reference simply reruns its whole O(N²) sweep.
    """
    done = {}
    if not path or not os.path.exists(path):
        return done
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
                if d.get("timed_out"):
                    continue  # re-measure wedged cells on resume
                # Transport joined the key in round 11; records from
                # earlier rounds carry none and were all XLA-measured.
                key = (d["workload"], d["direction"], d["src"], d["dst"],
                       d["msg_bytes"], d["mode"],
                       d.get("transport", "xla"))
                done[key] = d.get("gbps", math.nan)
            except (json.JSONDecodeError, KeyError):
                continue  # torn write from an interrupted run
    return done
