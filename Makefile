# Build system for the TPU-native P2P benchmark framework.
#
# The reference Makefile (/root/reference/Makefile:1-5) has one rule —
# `nvcc -lmpi -lnccl p2p_matrix.cc -o p2p_matrix` — and a broken
# `clean` (removes the wrong filename, Makefile:5). Per SURVEY.md L0,
# the TPU build needs no GPU toolchain: `device=tpu` is a Python entry
# point over jax[tpu]; the only native artifact is the host-side
# support library (timing/hashing/stats — native/tpu_p2p_native.cc).

CXX      ?= g++
CXXFLAGS ?= -O2 -fPIC -std=c++17 -Wall -Wextra
PYTHON   ?= python

NATIVE_SO := native/libtpu_p2p_native.so

.PHONY: all native run test bench clean

all: native

native: $(NATIVE_SO)

$(NATIVE_SO): native/tpu_p2p_native.cc
	$(CXX) $(CXXFLAGS) -shared $< -o $@

# `make run device=tpu` — the TPU driver (the reference's
# `mpirun -n N p2p_matrix`, README.md:5, becomes a plain Python entry:
# JAX enumerates the slice's devices itself). Extra flags via ARGS=.
run: native
	$(PYTHON) -m tpu_p2p $(ARGS)

test:
	$(PYTHON) -m pytest tests/ -x -q

bench: native
	$(PYTHON) bench.py

# `make train ARGS="--steps 100 --ckpt-dir runs/a"` — the training
# loop (tpu_p2p/train.py): loader + step + checkpoint/resume + JSONL.
train:
	$(PYTHON) -m tpu_p2p.train $(ARGS)

clean:
	rm -f $(NATIVE_SO)
