# Build system for the TPU-native P2P benchmark framework.
#
# The reference Makefile (/root/reference/Makefile:1-5) has one rule —
# `nvcc -lmpi -lnccl p2p_matrix.cc -o p2p_matrix` — and a broken
# `clean` (removes the wrong filename, Makefile:5). Per SURVEY.md L0,
# the TPU build needs no GPU toolchain: `device=tpu` is a Python entry
# point over jax[tpu]; the only native artifact is the host-side
# support library (timing/hashing/stats — native/tpu_p2p_native.cc).

CXX      ?= g++
CXXFLAGS ?= -O2 -fPIC -std=c++17 -Wall -Wextra
PYTHON   ?= python

# tier1 needs bash (pipefail / PIPESTATUS); harmless for every other
# recipe here.
SHELL    := /bin/bash

NATIVE_SO := native/libtpu_p2p_native.so

.PHONY: all native run test tier1 bench obs topo zb trace health serve serve-disagg serve-chaos reuse ckpt-chaos clean

all: native

native: $(NATIVE_SO)

$(NATIVE_SO): native/tpu_p2p_native.cc
	$(CXX) $(CXXFLAGS) -shared $< -o $@

# `make run device=tpu` — the TPU driver (the reference's
# `mpirun -n N p2p_matrix`, README.md:5, becomes a plain Python entry:
# JAX enumerates the slice's devices itself). Extra flags via ARGS=.
run: native
	$(PYTHON) -m tpu_p2p $(ARGS)

# Aligned with the graded tier-1 selection: slow-marked tests are
# excluded (they run in uncapped full passes) and collection errors
# don't abort the rest of the suite.
test:
	$(PYTHON) -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors

# The exact ROADMAP.md tier-1 verify command (870 s wall cap, CPU
# platform, DOTS_PASSED summary line).
tier1:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

bench: native
	$(PYTHON) bench.py

# Observability report + bench regression gate (docs/observability.md):
# live collective-ledger capture, then the BENCH_r*.json trajectory
# gate — nonzero exit on regression, so CI can gate on it.
obs:
	$(PYTHON) -m tpu_p2p obs $(ARGS)

# Topology-engine smoke (docs/topology.md): a deterministic FaultPlan
# link throttle, the host-timed probe seeing it, and the placement
# optimizers (ring order + KV-migration placement) routing around it
# while bitwise parity pins that re-placement never changes computed
# values — nonzero exit unless both optimizers beat the naive
# placement's predicted cost. Defaults to the simulated 8-device CPU
# mesh so it runs anywhere; override with ARGS= on real hardware.
topo:
	$(PYTHON) -m tpu_p2p topo --smoke $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# Zero-bubble schedule smoke (docs/schedule_ir.md): the fused
# production step (masked tick lowering) vs the zb route under the
# cost-proportional switch lowering (ZB-H1 weight split — GEMM-only
# dW ticks against the boundary stash) on a pure-pp mesh — bitwise
# loss parity pinned, then the wall-clock grade: nonzero exit unless
# zb beats the fused step where the analytic model says it must
# (must-not-lose on a single chip, where compile_zb degrades to the
# fused schedule). Defaults to the simulated 8-device CPU mesh so it
# runs anywhere; override with ARGS= on real hardware.
zb:
	$(PYTHON) -m tpu_p2p zb $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# Tick flight recorder smoke (docs/tracing.md): measured per-(rank,
# tick) timelines joined to the compiled Tick IR + the Chrome-trace
# export — nonzero exit unless the measured zb per-rank bubble
# ordering matches the analytic per_rank_idle ordering (idle-tick
# placement graded under the switch lowering), the per-tick constant-
# overhead estimate is nonzero, and the export schema-validates.
# Defaults to the simulated 8-device CPU mesh so it runs anywhere;
# override with ARGS= on real hardware.
trace:
	$(PYTHON) -m tpu_p2p obs trace $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# Injected-fault health smoke (docs/health.md): degraded link,
# straggler rank, and lost host + self-healing resume, each detected
# by tpu_p2p/obs/health.py on a deterministic fault plan — nonzero
# exit unless every detector fires within the gate's detect-steps
# budget and the heal's loss parity holds. Defaults to the simulated
# 8-device CPU mesh so it runs anywhere; override with ARGS= (e.g. an
# empty ARGS="--steps 12" on real hardware).
health:
	$(PYTHON) -m tpu_p2p obs smoke $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# Serving-engine smoke (docs/serving.md): paged KV cache + continuous
# batching over a synthetic Poisson trace, continuous-vs-static A/B on
# the same requests. Defaults to the simulated 8-device CPU mesh so it
# runs anywhere; override with ARGS= on real hardware.
serve:
	$(PYTHON) -m tpu_p2p serve $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# Disaggregated prefill/decode serving smoke (docs/serving_disagg.md):
# tp-heavy prefill submesh + dp decode replicas with ledger-priced
# KV-page migration between them, then the colocated continuous twin
# on the same trace — nonzero exit unless every completed request's
# token stream is BITWISE the colocated engine's. Defaults to the
# simulated 8-device CPU mesh (prefill 1×tp4 / 4 decode replicas);
# override with ARGS= on real hardware.
serve-disagg:
	$(PYTHON) -m tpu_p2p serve --disagg $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# KV-reuse graded smoke (docs/kv_reuse.md): one seeded shared-prefix
# burst trace served three ways — baseline, copy-on-write prefix
# cache, seeded draft-verify speculative decoding — graded on mean
# TTFT (in scheduler steps) collapsing below 0.5x baseline and on
# accepted tokens per decode step exceeding 1.0, each under BITWISE
# token-stream parity with the baseline engine; nonzero exit unless
# both grade. Prints NULL (exit 0) on <2-device meshes — per-shard
# sharing grades nothing there. Defaults to the simulated 8-device
# CPU mesh; override with ARGS= on real hardware.
reuse:
	$(PYTHON) -m tpu_p2p serve --reuse $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# Serving-resilience chaos smoke (docs/serving_resilience.md): three
# injected fault scenarios — page-pool clamp → preemption with zero
# completed-token loss + paged-vs-dense parity, request storm → shed
# verdicts within the step bound, slow host → bitwise schedule
# invariance — graded the way `make health` grades training; nonzero
# exit unless all three pass. Defaults to the simulated 8-device CPU
# mesh; override with ARGS= on real hardware.
serve-chaos:
	$(PYTHON) -m tpu_p2p serve --chaos $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# Checkpoint-durability chaos smoke (docs/checkpoint_durability.md):
# three injected storage-fault scenarios — crash mid-write →
# supervisor re-entry from the newest intact generation, corrupt
# newest generation → verifying-loader fallback with the skip reason
# surfaced, transient IO errors → bounded retry with zero fallbacks —
# each graded bitwise against an uninterrupted twin; nonzero exit
# unless all three scenarios grade. Defaults to the simulated
# 8-device CPU mesh; override with ARGS= on real hardware.
ckpt-chaos:
	$(PYTHON) -m tpu_p2p obs ckpt-smoke $(if $(ARGS),$(ARGS),--cpu-mesh 8)

# `make train ARGS="--steps 100 --ckpt-dir runs/a"` — the training
# loop (tpu_p2p/train.py): loader + step + checkpoint/resume + JSONL.
train:
	$(PYTHON) -m tpu_p2p.train $(ARGS)

# Unlike the reference's famously broken `clean` (removed the wrong
# filename, reference Makefile:5), this removes everything a build or
# test run leaves behind: the native .so, the bytecode caches, and
# pytest's cache.
clean:
	rm -f $(NATIVE_SO)
	rm -rf __pycache__ docs/__pycache__ .pytest_cache
	rm -rf tpu_p2p/parallel/__pycache__
	find tpu_p2p tests -name __pycache__ -type d -prune -exec rm -rf {} + 2>/dev/null || true
	# Pallas/Mosaic lowering caches the round-11 dma kernels can leave
	# behind (real-TPU runs; interpret mode writes none).
	rm -rf .mosaic_cache mosaic_cache __pallas_cache__ .pallas_cache
